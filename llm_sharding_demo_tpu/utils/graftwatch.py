"""graftwatch: telemetry-calibrated cost model + live re-planning.

The dynamic half of the graftcheck watch pass (``tools/graftcheck/
watch.py`` is the static half — the same static+dynamic split as
graftsan/graftlock/graftfault/graftload). This module closes ROADMAP
item 5's measure->model loop: the spine *measures* everything
(graftscope occupancy series, graftload goodput, the fleet router's
affinity/shed counters) and *plans* from an a-priori cost model
(graftplan) — graftwatch is where the two halves meet, the "Learning
to Shard" RL-co-optimization loop run inside the repo's own certifier
as the safety envelope.

Three pieces:

**Telemetry watcher** (:class:`TelemetryWatcher`): folds the live
signals into a windowed traffic-mix estimate. Every consumed signal is
DECLARED in ``PLAN_SIGNALS`` — a mapping from the watcher's fixed
``SIGNALS`` vocabulary to the ``METRIC_CATALOG`` series it is computed
from (the mirror of loadgen's ``SLO_SOURCE_METRICS``); the watch pass
verifies each mapped series exists and is really emitted, so the
re-planner can never watch a number nobody measures. The DECISION
inputs are deliberately narrower than the telemetry view: per-request
observations ``(prompt_len, max_new, pending)`` recorded at admission,
reduced order-independently (medians + window max), so the same
admitted request set produces the same estimate regardless of thread
interleaving — the replay-identity contract switch decisions inherit.

**Calibration** (:func:`fit_cost_weights`): extends
``costmodel.calibrate``'s single ICI byte weight to a fitted
per-primitive pair. The journaled ``graftscope_attribution`` drift rows
carry measured device s/token against modeled B/token per certified
workload; a least-squares fit through the origin recovers
``hbm_seconds_per_byte`` (what one streamed HBM byte costs this host)
and, when any row moves ICI bytes, the RELATIVE ``ici_byte_weight`` the
cost model's ranking uses (falling back to the journal's
``ici_byte_weight_calibration`` row via ``costmodel.calibrate``).
Present-but-unparsable rows raise ``costmodel.CalibrationError``
(typed, like every other contract violation); genuinely skipped rows
contribute nothing.

**Live re-planning** (:class:`PlanSwitcher` + ``AUTO_PLAN_CONTINUOUS=1``
in serving/app.py): a small plan set is PRE-CERTIFIED at startup — the
front ends are built once, over ONE shared engine and ONE shared block
pool, and each plan's compiled-program cost is proven by the
``recompile`` certifier machinery (``certify_plan_set``). Between
request waves (every ``wave`` admissions) the switcher scores the
certified plans against the watcher's windowed estimate with the
calibrated weights and installs the winner. The pinned invariant:
**a plan switch causes zero recompiles beyond the certified set** —
switching only re-routes admissions between pre-built front ends that
share every compiled program population; the switcher can never
construct a runner, and a switch target outside the certified set is a
typed error (``UncertifiedPlanError``), statically excluded by the
watch pass's ``uncertified-plan-switch`` rule. Every wave evaluation is
journaled as a replay-identical event: the decision is a pure function
of the windowed estimate + static plan costs + calibrated weights
(:func:`decide_plan` — same purity contract as FaultPlan/GRAFTSCHED),
and the event records exactly those inputs. The whole decision state is
served at ``GET /debug/plan``; ``/healthz`` ``auto_plan`` reports the
LIVE plan, not the startup choice.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import graftsched, graftscope, grafttime

# Lock-discipline contract (tools/graftcheck locks pass): the watcher's
# observation window and the switcher's active-plan/in-flight/event
# state are touched from arbitrary handler threads; each lives under
# its owning instance's ``_lock``. The two locks never nest (admission
# takes them strictly in sequence), so no order relation is declared
# beyond the single name.
GUARDED_STATE = {"_window": "_lock", "_admitted": "_lock",
                 "_active": "_lock", "_inflight": "_lock",
                 "_events": "_lock", "_switches": "_lock",
                 "_sizings": "_lock"}
LOCK_ORDER = ("_lock",)

# Timeline contract (tools/graftcheck timeline pass): every wave
# evaluation — and every actual switch — lands on the unified causal
# stream (utils/grafttime), so the signals that provoked a plan change
# are visible on the same clock as the change itself ("Learning to
# Shard" decisions become auditable, not just journaled).
TIMELINE_EVENTS = {
    "plan_eval": "PlanSwitcher._evaluate",
    "plan_switch": "PlanSwitcher._evaluate",
}

# -- declared signal provenance (the static watch pass reads these) ----------

# The watcher's fixed consumed-signal vocabulary (the watch pass rejects
# PLAN_SIGNALS keys outside it, and SIGNALS entries with no mapping).
SIGNALS = ("queue_depth", "batch_occupancy", "pool_blocks", "live_rows",
           "breaker_open", "prefix_hits", "prefix_misses",
           "admission_sheds", "affinity_hits", "affinity_fallbacks",
           "replica_sheds")

# signal -> the METRIC_CATALOG series it is computed from (the mirror of
# loadgen's SLO_SOURCE_METRICS; tools/graftcheck/watch.py verifies every
# mapped series exists in the catalog and is emitted at a live call
# site — a re-planner watching a series nobody emits would converge on
# noise). Gauges are read off the graftscope occupancy rings (the
# /debug/profile timeline), counters off the serving registry.
PLAN_SIGNALS = {
    "queue_depth": "queue_depth",
    "batch_occupancy": "batch_occupancy",
    "pool_blocks": "kv_cache_blocks_in_use",
    "live_rows": "iter_live_rows",
    "breaker_open": "hop_breaker_open",
    "prefix_hits": "prefix_cache_hits_total",
    "prefix_misses": "prefix_cache_misses_total",
    "admission_sheds": "kv_pool_admission_rejections_total",
    "affinity_hits": "fleet_affinity_hits_total",
    "affinity_fallbacks": "fleet_affinity_fallbacks_total",
    "replica_sheds": "fleet_sheds_total",
}

# The switchable plan set. Every label the switcher can ever install
# must be declared here, and every label must be constructed (and
# certified) by one of the PLAN_BUILDERS functions — the watch pass's
# uncertified-plan-switch rule holds both directions, which is the
# static half of the "no switch path can reach an uncertified program
# key" invariant (PlanSwitcher enforces the dynamic half with typed
# errors).
PLAN_SET = ("solo", "batched")
PLAN_BUILDERS = ("build_plan_set", "certify_plan_set", "plan_costs")


def signal_series(signal: str) -> str:
    """The METRIC_CATALOG series a consumed signal is computed from —
    THE provenance choke point: every read of live telemetry by name
    resolves through the declared mapping, never a bare string."""
    try:
        return PLAN_SIGNALS[signal]
    except KeyError:
        raise KeyError(
            f"unknown plan signal {signal!r}; declared: {SIGNALS}"
        ) from None


class UncertifiedPlanError(ValueError):
    """A switch path reached a plan label outside the certified set —
    the dynamic half of the watch pass's uncertified-plan-switch rule."""


# -- windowed traffic-mix estimate -------------------------------------------


@dataclasses.dataclass(frozen=True)
class TrafficEstimate:
    """The windowed mix the decision function consumes. All fields are
    ORDER-INDEPENDENT reductions of the admission window (medians over
    the multiset, max over pending), so any interleaving of the same
    admitted requests yields the same estimate — which is what makes
    the journaled switch events replay-identical."""

    requests: int = 0
    prompt_p50: int = 0
    max_new_p50: int = 0
    # 1 + the window's max in-flight count observed at admission: the
    # effective batch the cost model's weight-stream amortization sees
    concurrency: int = 1

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _median_int(values: List[int]) -> int:
    if not values:
        return 0
    vs = sorted(values)
    return int(vs[(len(vs) - 1) // 2])


class TelemetryWatcher:
    """Windowed traffic-mix estimator over per-request admission
    observations, plus the declared-signal telemetry view
    (:meth:`signals`) the /debug/plan payload serves."""

    def __init__(self, window: int = 16, registry=None):
        if window < 1:
            raise ValueError("window must be >= 1")
        from .metrics import REGISTRY
        self.registry = registry if registry is not None else REGISTRY
        self._lock = graftsched.lock("graftwatch.TelemetryWatcher._lock")
        self._window: deque = deque(maxlen=window)
        self._admitted = 0

    def observe(self, prompt_len: int, max_new: int,
                pending: int) -> int:
        """Record one admission; returns the total admitted so far (the
        switcher's wave counter). ``pending`` is the number of requests
        already in flight when this one was admitted."""
        with self._lock:
            self._window.append((int(prompt_len), int(max_new),
                                 int(pending)))
            self._admitted += 1
            return self._admitted

    def admitted(self) -> int:
        with self._lock:
            return self._admitted

    def estimate(self) -> TrafficEstimate:
        with self._lock:
            rows = list(self._window)
        if not rows:
            return TrafficEstimate()
        return TrafficEstimate(
            requests=len(rows),
            prompt_p50=_median_int([r[0] for r in rows]),
            max_new_p50=_median_int([r[1] for r in rows]),
            concurrency=1 + max(r[2] for r in rows))

    def signals(self, since_ms: Optional[float] = None) -> dict:
        """The declared-signal telemetry view: per consumed signal, the
        live reduction of its mapped series — gauge signals reduce the
        graftscope occupancy ring (points/mean/max/last, optionally
        windowed to ``since_ms`` on the snapshot timeline), counter
        signals read the registry's current totals summed over label
        sets. Purely observational (the decision function never reads
        this — see the module docstring's purity contract); served at
        /debug/plan so an operator can see what the watcher sees."""
        from .metrics import METRIC_CATALOG
        # the totals-only read: never builds the dispatch snapshot
        # under the lock every instrumented jit dispatch contends on
        series = graftscope.series_totals()
        flat = self.registry.snapshot()
        out: Dict[str, dict] = {}
        for signal in SIGNALS:
            name = signal_series(signal)
            kind = METRIC_CATALOG.get(name)
            if kind == "gauge":
                rows = {label: dict(tot) for label, tot in series.items()
                        if label == name or label.startswith(name + "{")}
                out[signal] = {"series": name, "kind": "gauge",
                               "points": rows}
            else:
                total = sum(v for key, v in flat.items()
                            if key == name or key.startswith(name + "{"))
                out[signal] = {"series": name, "kind": "counter",
                               "total": total}
        return out


# -- calibrated cost weights -------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CostWeights:
    """The calibrated per-primitive byte weights plan scoring uses.
    ``hbm_seconds_per_byte`` converts a modeled byte cost into predicted
    device seconds on THIS host (None until a journal is fitted);
    ``ici_byte_weight`` is the cost model's RELATIVE ICI-vs-HBM weight
    (None -> the a-priori ``costmodel.ICI_BYTE_WEIGHT``)."""

    hbm_seconds_per_byte: Optional[float] = None
    ici_byte_weight: Optional[float] = None
    per_scope_seconds: Tuple[Tuple[str, float], ...] = ()
    rows_used: int = 0
    source: str = "a-priori"

    @classmethod
    def apriori(cls) -> "CostWeights":
        return cls()

    def to_dict(self) -> dict:
        return {
            "hbm_seconds_per_byte": self.hbm_seconds_per_byte,
            "ici_byte_weight": self.ici_byte_weight,
            "per_scope_seconds": {k: round(v, 6)
                                  for k, v in self.per_scope_seconds},
            "rows_used": self.rows_used,
            "source": self.source,
        }


def _attribution_row(journal) -> Optional[dict]:
    """The ``graftscope_attribution`` config row out of a bench journal
    (raw payload, ``parsed`` driver wrapper, or the bare row itself) —
    the same acceptance envelope as ``costmodel.calibrate``."""
    doc = journal
    if isinstance(doc, dict) and "parsed" in doc:
        doc = doc.get("parsed")
    if not isinstance(doc, dict):
        return None
    if doc.get("name") == "graftscope_attribution":
        return doc
    for cfg in doc.get("configs") or ():
        if isinstance(cfg, dict) \
                and cfg.get("name") == "graftscope_attribution":
            return cfg
    return None


def fit_cost_weights(journal) -> CostWeights:
    """Fit per-primitive byte weights from a bench journal's
    ``graftscope_attribution`` drift rows (measured device s/token vs
    modeled B/token per certified workload).

    Each usable workload row contributes one equation
    ``measured_s = w_hbm * hbm_bytes + w_ici_s * comm_bytes`` (the
    modeled HBM term is the row's total byte cost with the a-priori ICI
    weighting removed); the least-squares solution through the origin
    gives ``hbm_seconds_per_byte`` and — when any row moved ICI bytes —
    the relative ``ici_byte_weight`` as the ratio of the two fitted
    rates. With zero ICI-moving rows (the CPU attribution set), the ICI
    weight falls back to the journal's ``ici_byte_weight_calibration``
    row via ``costmodel.calibrate``.

    Returns the a-priori weights (``rows_used == 0``) when the journal
    carries no attribution row or only skipped rows; raises
    ``costmodel.CalibrationError`` when a row is PRESENT but
    unparsable — a malformed measurement must fail loudly, never score
    plans as if it had been read."""
    from tools.graftcheck import costmodel as C
    row = _attribution_row(journal)
    # calibrate's CalibrationError propagates: a malformed ICI row must
    # fail this fit too, never degrade it to a-priori weights
    ici = C.calibrate(journal)
    if row is None or row.get("skipped") or row.get("error"):
        return CostWeights(ici_byte_weight=ici,
                           source="a-priori" if ici is None
                           else "ici-row-only")
    workloads = row.get("workloads")
    if not isinstance(workloads, list):
        raise C.CalibrationError(
            "graftscope_attribution row carries no 'workloads' list — "
            "present but unparsable (malformed journal?)")
    eqs: List[Tuple[float, float, float]] = []   # (hbm, comm, measured)
    scope_secs: Dict[str, float] = {}
    for wl in workloads:
        if not isinstance(wl, dict):
            raise C.CalibrationError(
                f"graftscope_attribution workload row is not an object: "
                f"{wl!r}")
        m = wl.get("measured_decode_seconds_per_token")
        if m is None:
            continue                      # honestly unmeasured: skip
        cost = wl.get("modeled_cost_bytes_per_token")
        comm = wl.get("modeled_comm_bytes_per_token", 0)
        if not isinstance(m, (int, float)) or isinstance(m, bool) \
                or not isinstance(cost, (int, float)) \
                or isinstance(cost, bool) or m <= 0 or cost <= 0 \
                or not isinstance(comm, (int, float)) \
                or isinstance(comm, bool) or comm < 0:
            raise C.CalibrationError(
                "graftscope_attribution workload "
                f"{wl.get('workload')!r}: measured/modeled fields are "
                "present but not positive numbers — refusing to fit "
                "weights from an unparsable row")
        # undo the a-priori ICI weighting baked into the scored total:
        # the attribution run priced comm at ICI_BYTE_WEIGHT
        hbm = float(cost) - C.ICI_BYTE_WEIGHT * float(comm)
        if hbm <= 0:
            raise C.CalibrationError(
                f"graftscope_attribution workload {wl.get('workload')!r}"
                ": modeled HBM term is non-positive after removing the "
                "ICI weighting — the row's byte split is inconsistent")
        eqs.append((hbm, float(comm), float(m)))
        for name, ep in (wl.get("entry_points") or {}).items():
            secs = (ep or {}).get("seconds_total")
            if isinstance(secs, (int, float)) and not isinstance(
                    secs, bool):
                scope_secs[name] = scope_secs.get(name, 0.0) + float(secs)
    if not eqs:
        return CostWeights(ici_byte_weight=ici,
                           source="ici-row-only" if ici is not None
                           else "a-priori")
    shh = sum(h * h for h, _, _ in eqs)
    shc = sum(h * c for h, c, _ in eqs)
    scc = sum(c * c for _, c, _ in eqs)
    shm = sum(h * m for h, _, m in eqs)
    scm = sum(c * m for _, c, m in eqs)
    det = shh * scc - shc * shc
    if scc > 0 and det > 0:
        w_h = (shm * scc - scm * shc) / det
        w_c = (scm * shh - shm * shc) / det
        if w_h > 0 and w_c > 0:
            ici = w_c / w_h
        else:                 # degenerate fit: keep the 1-D projection
            w_h = shm / shh
    else:
        w_h = shm / shh
    return CostWeights(
        hbm_seconds_per_byte=(w_h if w_h > 0 else None),
        ici_byte_weight=ici,
        per_scope_seconds=tuple(sorted(scope_secs.items())),
        rows_used=len(eqs),
        source="graftscope_attribution")


# -- the certified plan set --------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PlanCost:
    """The static per-plan cost terms (costmodel's decode-cost formula
    with the traffic-dependent amortization factored out) — precomputed
    at startup so wave-boundary scoring is a handful of float ops."""

    label: str
    batch_mode: str
    max_batch: int
    param_bytes: int
    kv_bytes_per_row: int
    paged_overhead: float
    comm_bytes: int = 0

    def simplicity(self) -> tuple:
        # the tie-break mirror of costmodel.PlanRow.sort_key: admission
        # before iter, narrower before wider
        return (self.batch_mode != "admission", self.max_batch)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def plan_costs(config, max_seq: int,
               max_batch: int) -> Dict[str, PlanCost]:
    """The switchable plans' static cost terms, from THE cost model's
    own byte math (``tools/graftcheck/costmodel``) — the planner that
    scored candidates at startup and the watcher that re-scores them
    live cannot use different arithmetic."""
    from llm_sharding_demo_tpu.models import family_module
    from tools.graftcheck import costmodel as C
    module = family_module(config)
    param_bytes = C.tree_bytes(C.param_avals(module, config))
    kv_row = C.kv_cache_bytes(config, 1, max_seq)
    paged_overhead = 2 * kv_row / C.PAGED_SEG_STEPS
    return {
        "solo": PlanCost(label="solo", batch_mode="admission",
                         max_batch=1, param_bytes=param_bytes,
                         kv_bytes_per_row=kv_row,
                         paged_overhead=paged_overhead),
        "batched": PlanCost(label="batched", batch_mode="iter",
                            max_batch=max_batch, param_bytes=param_bytes,
                            kv_bytes_per_row=kv_row,
                            paged_overhead=paged_overhead),
    }


def certify_plan_set(config, max_seq: int, max_batch: int,
                     pool_blocks: int, block_size: int,
                     traffic=None) -> Dict[str, dict]:
    """Prove the compiled-program cost of every switchable plan through
    the EXISTING certifier machinery (``recompile`` via
    ``costmodel.count_programs``) for the declared traffic classes.
    The solo row is exact (certified == observed, the recompile.certify
    guarantee); the iter row is the documented static bound over live
    widths 1..max_batch. The switcher journals these and refuses any
    label without an entry — no switch path can reach an uncertified
    program key."""
    from tools.graftcheck import costmodel as C
    if isinstance(traffic, str):
        traffic = C.parse_traffic(traffic)
    traffic = tuple(traffic) if traffic else C.DEFAULT_TRAFFIC
    cands = {
        "solo": C.Candidate(topology="single", batch_mode="admission",
                            max_batch=1, kv_pool_blocks=pool_blocks,
                            kv_block_size=block_size),
        "batched": C.Candidate(topology="single", batch_mode="iter",
                               max_batch=max_batch,
                               kv_pool_blocks=pool_blocks,
                               kv_block_size=block_size),
    }
    out: Dict[str, dict] = {}
    for label, cand in cands.items():
        programs, exact = C.count_programs(cand, max_seq, traffic)
        out[label] = {
            "programs": dict(programs),
            "program_total": sum(programs.values()),
            "programs_exact": exact,
            "candidate": dataclasses.asdict(cand),
        }
    return out


def build_plan_set(engine, pool, config, max_seq: int, max_batch: int,
                   traffic=None, batch_wait_ms: float = 5.0,
                   ) -> Tuple[Dict[str, object], Dict[str, PlanCost],
                              Dict[str, dict]]:
    """Construct the switchable front ends over ONE shared engine and
    ONE shared block pool — built once, at startup, which is the whole
    recompile argument: a switch re-routes admissions between runners
    whose compiled-program populations already exist; it can never
    construct a runner (and therefore never mint a program population
    the certifier did not price). Returns ``(plans, costs, certified)``
    keyed by ``PLAN_SET``."""
    from llm_sharding_demo_tpu.runtime.iterbatch import IterBatchingEngine
    from llm_sharding_demo_tpu.runtime.kv_pool import PagedKVRunner
    plans = {
        "solo": PagedKVRunner(engine, pool),
        "batched": IterBatchingEngine(engine, max_batch=max_batch,
                                      max_wait_ms=batch_wait_ms,
                                      pool=pool),
    }
    costs = plan_costs(config, max_seq, max_batch)
    certified = certify_plan_set(config, max_seq, max_batch,
                                 pool.allocator.num_blocks,
                                 pool.block_size, traffic=traffic)
    return plans, costs, certified


# -- the pure decision function ----------------------------------------------


def score_plans(estimate: TrafficEstimate,
                costs: Dict[str, PlanCost],
                weights: CostWeights) -> Dict[str, float]:
    """Modeled decode byte-cost per token of each certified plan under
    the estimated mix — costmodel.score_candidate's formula with the
    calibrated ICI weight, restricted to the static terms the plan set
    spans. Pure: same (estimate, costs, weights) -> same scores. (The
    a-priori import only fires with an unresolved weight — the
    switcher pre-resolves its weights at construction so the
    wave-boundary path never pays import machinery under its hold.)"""
    ici_w = weights.ici_byte_weight
    if not ici_w:
        from tools.graftcheck.costmodel import ICI_BYTE_WEIGHT as ici_w
    out: Dict[str, float] = {}
    for label, pc in costs.items():
        eff = max(1, min(pc.max_batch, estimate.concurrency))
        out[label] = (pc.param_bytes / eff + pc.kv_bytes_per_row
                      + pc.paged_overhead + ici_w * pc.comm_bytes)
    return out


def decide_plan(estimate: TrafficEstimate, costs: Dict[str, PlanCost],
                weights: CostWeights, current: str,
                margin: float = 0.1) -> Tuple[str, Dict[str, float]]:
    """The switch decision: best-scoring plan (simplicity tie-break, the
    sort_key mirror), installed only past the hysteresis ``margin`` —
    unless the best plan is no costlier AND simpler, which is the
    traffic-drained switch-back (equal scores, narrower plan wins).
    PURE (no clock, no RNG, no ambient state): the journaled event's
    inputs replay to the journaled decision, the FaultPlan/GRAFTSCHED
    replay-identity contract."""
    scores = score_plans(estimate, costs, weights)
    best = min(costs, key=lambda lb: (scores[lb],
                                      costs[lb].simplicity(), lb))
    return _pick(best, current, scores, costs, margin), scores


def _pick(best: str, current: str, scores: Dict[str, float],
          costs: Dict[str, PlanCost], margin: float) -> str:
    """Hysteresis: install ``best`` only past ``margin``, or on an
    equal score when it is strictly simpler (the switch-back path)."""
    if best == current:
        return current
    cur = scores.get(current)
    if cur is None:
        return best
    if scores[best] < cur * (1.0 - margin):
        return best
    if scores[best] <= cur and costs[best].simplicity() \
            < costs[current].simplicity():
        return best
    return current


# -- the switcher ------------------------------------------------------------


class PlanSwitcher:
    """Routes admissions to the active pre-certified plan and
    re-evaluates between request waves. Every label it can install is
    pinned to the certified set at construction (typed
    ``UncertifiedPlanError`` otherwise); every wave evaluation is
    journaled with its full decision inputs."""

    HISTORY = 128       # bounded event journal (a ring, not a log)

    def __init__(self, plans: Dict[str, object],
                 costs: Dict[str, PlanCost],
                 certified: Dict[str, dict],
                 watcher: TelemetryWatcher,
                 weights: Optional[CostWeights] = None,
                 initial: Optional[str] = None, wave: int = 8,
                 margin: float = 0.1, registry=None):
        if not plans:
            raise ValueError("empty plan set")
        labels = set(plans)
        if labels != set(costs) or labels != set(certified):
            raise UncertifiedPlanError(
                f"plan set {sorted(labels)} does not match costs "
                f"{sorted(costs)} / certified {sorted(certified)} — "
                "every switchable plan must be priced AND certified")
        for label in labels:
            if label not in PLAN_SET:
                raise UncertifiedPlanError(
                    f"plan label {label!r} is not in the declared "
                    f"PLAN_SET {PLAN_SET}")
        if wave < 1:
            raise ValueError("wave must be >= 1")
        from .metrics import REGISTRY
        self.registry = registry if registry is not None else REGISTRY
        self.plans = dict(plans)
        self.costs = dict(costs)
        self.certified = dict(certified)
        self.watcher = watcher
        self.weights = weights if weights is not None \
            else CostWeights.apriori()
        if not self.weights.ici_byte_weight:
            # resolve the a-priori weight ONCE, here, so the
            # wave-boundary decision path never imports under its hold
            from tools.graftcheck.costmodel import ICI_BYTE_WEIGHT
            self.weights = dataclasses.replace(
                self.weights, ici_byte_weight=ICI_BYTE_WEIGHT)
        self.wave = int(wave)
        self.margin = float(margin)
        self._lock = graftsched.lock("graftwatch.PlanSwitcher._lock")
        # start on the simplest plan (the costmodel tie-break): under
        # the default single-stream estimate every plan scores equal,
        # and simplicity is the declared preference
        start = initial if initial is not None else min(
            self.costs, key=lambda lb: (self.costs[lb].simplicity(), lb))
        if start not in self.plans:
            raise UncertifiedPlanError(
                f"initial plan {start!r} is not in the certified set "
                f"{sorted(self.plans)}")
        self._active = start
        self._inflight = 0
        self._switches = 0
        self._events: deque = deque(maxlen=self.HISTORY)
        # trend-driven sizing (grafttrend.SIZING_POLICY): attached via
        # attach_trend; base knob values are captured at attach so a
        # resize is always BASE x scale, never compounding drift
        self._trend = None
        self._sizing_base: Dict[str, tuple] = {}
        self._sizings: deque = deque(maxlen=self.HISTORY)
        self._announce(start)

    # -- admission routing --

    def peek(self):
        """The active runner, without admitting work (serving's 429
        gate reads this before committing the request)."""
        with self._lock:
            return self.plans[self._active]

    def admit(self, prompt_len: int, max_new: int):
        """Observe one admission, evaluate at wave boundaries, and
        return ``(runner, label)`` for THIS request. Pair with
        :meth:`release` (try/finally) so the in-flight estimate stays
        conservation-true."""
        with self._lock:
            pending = self._inflight
            self._inflight += 1
        n = self.watcher.observe(prompt_len, max_new, pending)
        if n % self.wave == 0:
            self._evaluate(n)
        with self._lock:
            label = self._active
            return self.plans[label], label

    def release(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    # -- the wave evaluation --

    def _evaluate(self, admitted: int) -> None:
        est = self.watcher.estimate()
        t_ms = round(graftscope.now_ms(), 3)
        switched_from: Optional[str] = None
        with self._lock:
            current = self._active
            # the decision runs pure float math over static inputs
            # (ici weight resolved at construction — no import, no
            # blocking call under this hold), and read+install is ONE
            # atomic region: a peer wave cannot interleave between
            # reading `current` and acting on it
            decision, scores = decide_plan(est, self.costs, self.weights,
                                           current, margin=self.margin)
            if decision not in self.plans:
                raise UncertifiedPlanError(
                    f"switch decision {decision!r} outside the "
                    f"certified set {sorted(self.plans)}")
            if decision != current:
                self._active = decision
                self._switches += 1
                switched_from = current
            self._events.append({
                "wave": admitted // self.wave,
                "admitted": admitted,
                "estimate": est.to_dict(),
                "scores": {lb: round(s, 1) for lb, s in scores.items()},
                "from": current,
                "to": decision,
                "switched": decision != current,
                # wall-clock context only — replay identity is over
                # the event MINUS this field (strip_time in events())
                "t_ms": t_ms,
            })
            # timeline emission UNDER the hold (the _sample_breaker
            # precedent: a cheap bounded ring append, never a blocking
            # call) — two racing wave evaluations must not publish
            # their eval/switch events in inverted order
            grafttime.emit("plan_eval", to_plan=decision,
                           from_plan=current,
                           wave=admitted // self.wave,
                           switched=switched_from is not None)
            if switched_from is not None:
                grafttime.emit("plan_switch", to_plan=decision,
                               from_plan=switched_from,
                               wave=admitted // self.wave)
        if switched_from is not None:
            self._announce(decision, previous=switched_from)
        self._resize(admitted // self.wave)

    # -- re-fitted weights (grafttrend.refit's threading hook) --

    def set_weights(self, weights: CostWeights) -> CostWeights:
        """Install re-fitted cost weights between waves — what
        ``grafttrend.refit`` calls after a live fit over the
        attribution rings. Scoring-only by construction:
        ``score_plans`` is linear in the ICI weight, so a change from
        w to w' shifts every plan score by exactly (w' - w) x that
        plan's ``comm_bytes`` (the calibration golden), and weights
        never key a compiled program — the pre-certified
        zero-recompile envelope is untouched. A missing ici weight
        resolves to the a-priori constant exactly as at construction.
        Returns the previous weights."""
        if not weights.ici_byte_weight:
            from tools.graftcheck.costmodel import ICI_BYTE_WEIGHT
            weights = dataclasses.replace(
                weights, ici_byte_weight=ICI_BYTE_WEIGHT)
        with self._lock:
            prev, self.weights = self.weights, weights
        return prev

    # -- trend-driven sizing (the ROADMAP item-7 "routes but doesn't
    # size" follow-on) --

    def attach_trend(self, reducer) -> None:
        """Attach a grafttrend reducer: between waves the switcher
        reads its windowed occupancy estimate and scales the declared
        ``grafttrend.SIZING_POLICY`` knobs — the batched plan's
        ``batch_wait_ms`` (``max_wait_s``) and admission watermark
        (``queue_limit``) — as BASE x clamp(estimate / max_batch,
        min_scale, max_scale). Both knobs are pure scheduling
        parameters: neither keys a compiled program (zero-recompile)
        nor changes any emitted token (byte-equal per request — the
        pinned contract in tests/test_grafttrend.py). Base values are
        captured HERE, once, so repeated resizes never compound."""
        self._trend = reducer
        self._sizing_base = {
            label: (runner.max_wait_s, runner.queue_limit,
                    runner.max_batch)
            for label, runner in self.plans.items()
            if hasattr(runner, "max_wait_s")
            and hasattr(runner, "queue_limit")}

    def _resize(self, wave: int) -> None:
        trend = self._trend
        if trend is None or not self._sizing_base:
            return
        from . import grafttrend
        # wave boundaries drive the reducer's ingestion too (bounded:
        # one registry fold per wave, OUTSIDE every switcher hold), so
        # sizing sees live occupancy without an external scraper
        trend.poll()
        series, lo, hi = grafttrend.SIZING_POLICY["batch_wait_ms"]
        q_series, q_lo, q_hi = grafttrend.SIZING_POLICY["queue_limit"]
        est = trend.occupancy_estimate(series)
        q_est = est if q_series == series \
            else trend.occupancy_estimate(q_series)
        if est is None and q_est is None:
            return   # silence never resizes: knobs stay where they are
        row = {"wave": wave, "estimate": None if est is None
               else round(est, 3), "knobs": {}}
        for label, (base_wait, base_limit, max_batch) in sorted(
                self._sizing_base.items()):
            runner = self.plans[label]
            if est is not None:
                scale = min(max(est / max(max_batch, 1), lo), hi)
                runner.max_wait_s = base_wait * scale
            if q_est is not None:
                q_scale = min(max(q_est / max(max_batch, 1), q_lo),
                              q_hi)
                runner.queue_limit = max(1, int(round(
                    base_limit * q_scale)))
            row["knobs"][label] = {
                "batch_wait_ms": round(runner.max_wait_s * 1e3, 4),
                "queue_limit": runner.queue_limit}
        with self._lock:
            self._sizings.append(row)

    def sizings(self, n: Optional[int] = None) -> List[dict]:
        """The journaled trend-driven resizes (oldest first, bounded)."""
        with self._lock:
            rows = [dict(r) for r in self._sizings]
        return rows if n is None else rows[-n:]

    def _announce(self, label: str, previous: Optional[str] = None):
        # metric emission stays OUTSIDE every hold (graftlock's
        # blocking-under-lock discipline)
        reg = self.registry
        if previous is not None:
            reg.inc("plan_switches_total", **{"from": previous,
                                              "to": label})
            reg.gauge("auto_plan_active", 0.0, plan=previous)
            graftscope.sample("auto_plan_active", 0.0, plan=previous)
        reg.gauge("auto_plan_active", 1.0, plan=label)
        graftscope.sample("auto_plan_active", 1.0, plan=label)

    # -- observability --

    def events(self, n: Optional[int] = None,
               strip_time: bool = False) -> List[dict]:
        """The journaled wave evaluations (oldest first, bounded).
        ``strip_time=True`` drops the wall-clock context field — what
        the replay-identity pins compare."""
        with self._lock:
            rows = list(self._events)
        if n is not None:
            rows = rows[-n:]
        if strip_time:
            rows = [{k: v for k, v in r.items() if k != "t_ms"}
                    for r in rows]
        return rows

    def switch_history(self, n: Optional[int] = None) -> List[dict]:
        return [e for e in self.events(n=None) if e["switched"]][
            -(n or self.HISTORY):]

    def health_view(self) -> dict:
        """The live /healthz ``auto_plan`` block: continuous mode's
        current state, not the startup choice."""
        # the watcher's lock is taken OUTSIDE the switcher's hold (the
        # declared contract: the two locks never nest)
        admitted = self.watcher.admitted()
        with self._lock:
            return {"mode": "continuous", "active": self._active,
                    "switches": self._switches,
                    "admitted": admitted,
                    "wave": self.wave,
                    "plans": sorted(self.plans)}

    def describe(self, n: int = 16) -> dict:
        """The GET /debug/plan payload body: current plan, candidate
        scores under the live estimate, calibrated weights, certified
        program costs, switch history, and the declared signal map."""
        est = self.watcher.estimate()
        scores = score_plans(est, self.costs, self.weights)
        with self._lock:
            active = self._active
            switches = self._switches
        rows = []
        for label in sorted(self.plans):
            pc = self.costs[label]
            cert = self.certified[label]
            row = {"label": label, "active": label == active,
                   "batch_mode": pc.batch_mode,
                   "max_batch": pc.max_batch,
                   "cost_terms": pc.to_dict(),
                   "score_bytes_per_token": round(scores[label], 1),
                   "certified": cert}
            if self.weights.hbm_seconds_per_byte:
                row["predicted_seconds_per_token"] = round(
                    scores[label] * self.weights.hbm_seconds_per_byte, 8)
            rows.append(row)
        return {
            "mode": "continuous",
            "active": active,
            "switches": switches,
            "wave": self.wave,
            "margin": self.margin,
            "admitted": self.watcher.admitted(),
            "estimate": est.to_dict(),
            "calibrated_weights": self.weights.to_dict(),
            "plans": rows,
            "events": self.events(n=n),
            "sizings": self.sizings(n=n),
            "signals": dict(PLAN_SIGNALS),
            "signal_values": self.watcher.signals(),
        }


# -- queue-depth ordering (the fleet router's prefill fanout) ----------------


def order_by_queue_depth(candidates: List[str],
                         depth_of: Dict[str, int]) -> List[str]:
    """Order replica names by the watcher's per-replica queue-depth
    estimate, ascending; the sort is STABLE, so callers pass candidates
    in their deterministic fallback order (the consistent-hash ring
    walk) and idle fleets keep the ring's warm-spread placement while a
    backed-up replica demotes past its peers. Pure — the seeded
    two-prefill-replica pin replays it exactly."""
    return sorted(candidates, key=lambda name: depth_of.get(name, 0))
