"""graftnum: the tolerance oracle for APPROXIMATE compute paths.

The repo's exactness discipline is byte-equality: every exact path
(paged ≡ contiguous, chunked ≡ monolithic, spec ≡ plain, fleet ≡
single) is pinned token-for-token. Approximate paths — weight-only int8
(``ops.quant``) and bf16 decode — deliberately break that contract, and
until now their quality claims lived in prose ("logits stay f32",
"within quantization error") that nothing measured on a pinned seed.
This module is the dynamic half of **graftnum** (the static half is
``tools/graftcheck/numerics.py``, the same split as graftsan/graftlock/
graftfault): a seeded, replay-identical oracle that runs an approximate
engine against its f32/exact sibling and holds the divergence to a
DECLARED budget.

Declarations (read statically by the numerics pass):

- ``REGIMES``: the dtype-regime vocabulary. ``DecodeEngine(dtype=...)``
  validates against it via :func:`regime_of` — an off-vocabulary dtype
  is a typed :class:`GraftnumError` at construction, not a silent
  ``astype`` to something no contract covers.
- ``TOLERANCE_POLICY``: ``{path: {"logit_mse": cap,
  "top1_agreement": floor}}`` — the declared quality budget per
  approximate path. Every ``PRECISION_CONTRACT`` entry with
  ``exact: False`` must name one of these paths (rule
  ``approx-without-oracle``), so an approximate path without a measured
  budget cannot ship.

Oracle methodology (:class:`ToleranceOracle`):

- Workloads are seeded and replay-identical: the k-th prompt for a path
  is a pure function of ``(seed, path, k)`` via
  ``random.Random(f"{seed}/{path}/{k}")`` — the FaultPlan/GRAFTSCHED/
  loadgen contract, so a breach reproduces from its report.
- Comparison is TEACHER-FORCED along the exact engine's greedy
  trajectory: at each step both engines score the SAME prefix (prompt +
  the exact stream's tokens), so per-position logit MSE and greedy
  top-1 agreement are position-aligned instead of measuring the chaos
  of diverged contexts (one flipped argmax rewrites all later context —
  stream distance measures conditioning, not quantization quality).
- Logits come from each engine's OWN compiled prefill entry point
  (``_prefill``), i.e. the production quantized/bf16 compute path, not
  a re-implementation.
- A breach raises a typed :class:`GraftnumError` carrying per-position
  provenance (prompt index, step, per-position MSE, both argmaxes), so
  the failing position is debuggable, not just the aggregate.

Consumers: the int8 weight-only path (``decode.int8``), bf16-vs-f32
decode (``decode.bf16``), and the quantized KV pool (``kv.int8`` /
``kv.fp8``): per-block narrow KV storage (runtime.kv_pool
``block_dtype``, ops.kv_quant) measured by this same oracle through
:class:`_QuantizedKVProbe` — the production pool movers
(quantize-on-scatter, dequant-on-gather) inserted into the exact
engine's own compiled forward, so the measured divergence is exactly
one pool round-trip per scored position.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

# The dtype-regime vocabulary. tools/graftcheck/numerics.py mirrors
# this as NUM_REGIMES (tests pin the two stay equal, like the slo
# pass's SLO_METRICS). ``fp8`` is a KV-block STORAGE regime only
# (runtime.kv_pool ``block_dtype`` / serving ``KV_POOL_DTYPE``);
# engines admit the first three via :func:`engine_regime_of`.
REGIMES = ("f32", "bf16", "int8", "fp8")

# Accepted spellings per regime (engine callers pass jnp dtypes, numpy
# dtypes, or serving-config strings; all collapse to one regime token).
# Both fp8 interchange formats collapse to one regime: the contract is
# about the quantize/dequantize boundary, and kv-block storage uses
# e4m3fn (ops.kv_quant.STORAGE_DTYPES — mantissa over exponent for
# absmax-normalized block content).
_REGIME_ALIASES = {
    "float32": "f32", "f32": "f32",
    "bfloat16": "bf16", "bf16": "bf16",
    "int8": "int8",
    "fp8": "fp8", "float8_e4m3fn": "fp8", "float8_e5m2": "fp8",
}

# Declared quality budgets per approximate path — the oracle's gate and
# the approx-without-oracle rule's registry. ``logit_mse`` is a CAP on
# the mean per-position MSE over the vocab (f32 logits, teacher-forced
# positions); ``top1_agreement`` is a FLOOR on the fraction of positions
# whose greedy argmax matches the exact path. Bounds carry ~100x
# headroom over values measured on the pinned bench seed (seed 0, demo
# model: 3.0e-7 bf16 / 1.7e-6 int8, agreement 1.0 both) so the gate
# catches step-function regressions (a lost f32 accumulator, a scale
# folded on the wrong axis — those move MSE by orders of magnitude),
# never round-off drift across hosts/BLAS builds.
TOLERANCE_POLICY = {
    # weight-only int8 decode (ops.quant) vs the f32 parity engine
    "decode.int8": {"logit_mse": 2e-4, "top1_agreement": 0.90},
    # bf16 decode (matmul operand rounding only; LN stats/softmax/
    # logits stay f32) vs the f32 parity engine
    "decode.bf16": {"logit_mse": 5e-5, "top1_agreement": 0.95},
    # quantized KV blocks (runtime.kv_pool block_dtype, ops.kv_quant):
    # the exact engine's own forward with one pool scatter/gather
    # round-trip on the KV cache per scored position
    # (_QuantizedKVProbe). Measured on seed 0 (demo model): 1.5e-8
    # int8 / 3.0e-7 fp8-e4m3fn, agreement 1.0 both — same ~100x
    # headroom convention as the decode paths. (int8 is TIGHTER than
    # fp8 here: 127 uniform levels beat e4m3's 3-bit mantissa on
    # absmax-normalized block content.)
    "kv.int8": {"logit_mse": 2e-6, "top1_agreement": 0.90},
    "kv.fp8": {"logit_mse": 3e-5, "top1_agreement": 0.90},
}


class GraftnumError(Exception):
    """Typed numerics-contract violation.

    Raised by :func:`regime_of` on an off-vocabulary dtype and by
    :class:`ToleranceOracle` on a tolerance breach; a breach carries
    ``path`` / ``metric`` / ``limit`` / ``observed`` plus ``positions``
    — the per-position provenance rows (prompt index, step, per-position
    logit MSE, exact vs approx argmax) sorted worst-first.
    """

    def __init__(self, message: str, path: Optional[str] = None,
                 metric: Optional[str] = None,
                 limit: Optional[float] = None,
                 observed: Optional[float] = None,
                 positions: Sequence[dict] = ()):
        super().__init__(message)
        self.path = path
        self.metric = metric
        self.limit = limit
        self.observed = observed
        self.positions = tuple(positions)


def regime_of(dtype) -> str:
    """Collapse a dtype spelling to its declared regime token.

    Accepts the declared regimes in any spelling (``jnp.float32`` /
    ``"bfloat16"`` / ``"int8"`` / ``"fp8"`` / numpy dtypes); anything
    else — ``"float16"``, a typo — raises a typed
    :class:`GraftnumError` instead of flowing into ``astype`` and
    silently running a precision nothing declared.
    """
    name = dtype if isinstance(dtype, str) else None
    if name is None:
        try:
            import jax.numpy as jnp
            name = jnp.dtype(dtype).name
        except TypeError:
            name = repr(dtype)
    regime = _REGIME_ALIASES.get(name)
    if regime is None:
        raise GraftnumError(
            f"dtype {dtype!r} is outside the declared regime vocabulary "
            f"{REGIMES} (spellings: float32/bfloat16/int8/fp8 and their "
            "jnp dtypes). Low-precision regimes are a declared contract "
            "(PRECISION_CONTRACT + TOLERANCE_POLICY, see "
            "docs/ARCHITECTURE.md 'Numerics discipline'); an undeclared "
            "dtype has no cast boundaries and no tolerance budget.")
    return regime


def engine_regime_of(dtype) -> str:
    """:func:`regime_of`, restricted to ENGINE compute regimes.

    ``fp8`` is in the declared vocabulary as a KV-block STORAGE regime
    (``runtime.kv_pool`` ``block_dtype`` / serving ``KV_POOL_DTYPE``) —
    no engine forward runs fp8 activations or weights, so an engine
    constructor passing it gets the same typed regime-vocabulary error
    an undeclared dtype would, pointing at the knob that does exist.
    """
    regime = regime_of(dtype)
    if regime == "fp8":
        raise GraftnumError(
            f"dtype {dtype!r} is outside the ENGINE regime vocabulary "
            f"{REGIMES[:-1]}: 'fp8' is a KV-block storage regime — set "
            "it per pool (KVBlockPool(block_dtype='fp8') / the serving "
            "KV_POOL_DTYPE knob), not as an engine compute dtype.")
    return regime


def _seeded_prompt(seed: int, path: str, k: int, vocab: int,
                   length: int) -> List[int]:
    """The k-th workload prompt: a pure function of (seed, path, k) —
    replay-identical like FaultPlan firings and loadgen arrivals."""
    rng = random.Random(f"{seed}/{path}/{k}")
    return [rng.randrange(vocab) for _ in range(length)]


class ToleranceOracle:
    """Seeded approximate-vs-exact comparison against declared budgets.

    One oracle instance fixes the workload schedule (``seed``,
    ``n_prompts``, ``prompt_len``, ``steps``); :meth:`compare` runs one
    approximate engine against its exact sibling and returns the
    JSON-able report (byte-identical across fresh runs with the same
    seed — pinned by tests), raising :class:`GraftnumError` with
    per-position provenance when the path's declared policy is
    breached. ``policy`` is injectable for fixtures; the default is the
    declared :data:`TOLERANCE_POLICY`.
    """

    def __init__(self, seed: int, policy: Optional[Dict] = None,
                 n_prompts: int = 3, prompt_len: int = 5, steps: int = 6):
        self.seed = seed
        self.policy = TOLERANCE_POLICY if policy is None else policy
        self.n_prompts = n_prompts
        self.prompt_len = prompt_len
        self.steps = steps

    def workloads(self, path: str, vocab: int) -> List[List[int]]:
        return [_seeded_prompt(self.seed, path, k, vocab, self.prompt_len)
                for k in range(self.n_prompts)]

    @staticmethod
    def _last_logits(engine, ids):
        """[1, S] ids -> [V] f32 last-position logits through the
        engine's OWN compiled prefill (the production quantized/bf16
        compute path — never a re-implementation)."""
        import jax.numpy as jnp
        import numpy as np
        logits, _cache = engine._prefill(engine._run_params(),
                                         jnp.asarray(ids, jnp.int32), None)
        return np.asarray(logits, dtype=np.float32)[0]

    def compare(self, path: str, approx_engine, exact_engine) -> dict:
        """Run ``path``'s seeded workloads through both engines and gate
        the divergence against the declared policy. Returns the report;
        raises :class:`GraftnumError` on breach."""
        import numpy as np

        if path not in self.policy:
            raise GraftnumError(
                f"approximate path {path!r} has no TOLERANCE_POLICY "
                f"entry (declared paths: {sorted(self.policy)}) — an "
                "approximate path without a declared budget cannot be "
                "gated", path=path)
        policy = self.policy[path]
        vocab = exact_engine.config.vocab_size
        positions: List[dict] = []
        for k, prompt in enumerate(self.workloads(path, vocab)):
            arr = np.asarray([prompt], dtype=np.int32)
            # teacher forcing: the exact engine's greedy stream is the
            # shared trajectory both sides score position-by-position
            forced = exact_engine.generate(arr, self.steps).tokens[
                0, len(prompt):].tolist()
            for t in range(self.steps):
                ids = [prompt + forced[:t]]
                le = self._last_logits(exact_engine, ids)
                la = self._last_logits(approx_engine, ids)
                mse = float(np.mean((la - le) ** 2))
                e_top, a_top = int(le.argmax()), int(la.argmax())
                positions.append({
                    "prompt": k, "step": t,
                    "logit_mse": round(mse, 12),
                    "exact_top1": e_top, "approx_top1": a_top,
                    "agree": e_top == a_top,
                })
        mse_mean = float(np.mean([p["logit_mse"] for p in positions]))
        agreement = float(np.mean([p["agree"] for p in positions]))
        report = {
            "path": path,
            "seed": self.seed,
            "n_prompts": self.n_prompts,
            "prompt_len": self.prompt_len,
            "steps": self.steps,
            "n_positions": len(positions),
            "logit_mse": round(mse_mean, 12),
            "top1_agreement": round(agreement, 6),
            "policy": dict(policy),
            "positions": positions,
        }
        if mse_mean > policy["logit_mse"]:
            worst = sorted(positions, key=lambda p: -p["logit_mse"])[:5]
            raise GraftnumError(
                f"path {path!r}: logit_mse {mse_mean:.3e} exceeds the "
                f"declared cap {policy['logit_mse']:.3e} (seed "
                f"{self.seed}; worst positions {worst})",
                path=path, metric="logit_mse",
                limit=policy["logit_mse"], observed=mse_mean,
                positions=worst)
        if agreement < policy["top1_agreement"]:
            worst = [p for p in positions if not p["agree"]][:5]
            raise GraftnumError(
                f"path {path!r}: top1_agreement {agreement:.4f} below "
                f"the declared floor {policy['top1_agreement']:.4f} "
                f"(seed {self.seed}; disagreeing positions {worst})",
                path=path, metric="top1_agreement",
                limit=policy["top1_agreement"], observed=agreement,
                positions=worst)
        return report


# Lease contract (tools/graftcheck sanitize pass): the probe's
# ``_prefill`` is the one scope here that moves pool blocks, and it
# brackets its movers with its own alloc/free (try/finally) — the
# lease is held for exactly the round-trip being measured.
POOL_MOVER_SCOPES = ("_QuantizedKVProbe._prefill",)


class _QuantizedKVProbe:
    """An "approximate engine" whose ONLY approximation is the
    quantized KV pool: the exact engine's own compiled programs, with
    the KV cache routed through the pool's production quantize-on-
    scatter / dequant-on-gather movers between prefilling the history
    and scoring the last position. The oracle's ``_last_logits`` call
    therefore measures exactly one pool round-trip of KV error per
    position — model weights, activations, and every other program are
    the exact engine's, so a budget breach localizes to the movers.

    Duck-types the slice of the engine surface the oracle touches:
    ``config``, ``_run_params``, ``_prefill``.
    """

    def __init__(self, engine, pool):
        if pool.block_dtype is None:
            raise GraftnumError(
                "probe pool stores full-precision blocks — the probe "
                "would measure a byte-identity, not a quantized path; "
                "construct the pool with block_dtype set")
        self.engine = engine
        self.pool = pool
        self.config = engine.config

    def _run_params(self):
        return self.engine._run_params()

    def _prefill(self, params, ids, pad):
        """[1, S] ids -> ([1, V] last-position logits, cache): prefill
        the first S-1 tokens exactly, round-trip that cache through the
        quantized pool (scatter = quantize, gather = dequantize), then
        score token S with the exact engine's cached forward on the
        dequantized working view."""
        import numpy as np

        eng, pool = self.engine, self.pool
        hist = int(ids.shape[1]) - 1
        _logits, cache = eng._prefill(params, ids[:, :-1], pad)
        row = pool.allocator.alloc(pool.nbm)
        tables = np.asarray([row], np.int32)
        try:
            pool.scatter(cache, tables)
            working = pool.gather(tables, hist)
            logits, working = eng._forward_cached(params, ids[:, -1:],
                                                  working, pad)
        finally:
            pool.allocator.free(row)
        return logits[:, -1], working


def oracle_rows(seed: int = 0, max_seq: int = 64) -> List[dict]:
    """The bench/CI consumer: run every declared TOLERANCE_POLICY path
    on the pinned demo model (fleet.harness.demo_model — the same
    geometry every harness serves) and return one compact report row
    per path (positions dropped; the oracle raises on breach, so a row
    existing means the path is inside its declared budget). A path
    whose backend prerequisite is missing (fp8 storage on an old chip)
    yields a ``{"skipped": reason}`` row — present, so the journal
    shows the gap, but unmeasured."""
    import jax.numpy as jnp

    from ..fleet.harness import demo_model
    from ..ops import kv_quant
    from ..runtime.engine import DecodeEngine
    from ..runtime.kv_pool import KVBlockPool
    from .metrics import DEFAULT_KV_BLOCK_SIZE

    cfg, params = demo_model(max_seq)
    exact = DecodeEngine(params, cfg, max_seq=max_seq)

    def kv_probe(block_dtype):
        # twice the one-row block count: headroom is irrelevant to the
        # oracle (one row at a time), this just keeps the allocator's
        # watermark out of the way
        pool = KVBlockPool.for_engine(
            exact, num_blocks=2 * (exact._cache_seq // DEFAULT_KV_BLOCK_SIZE),
            block_dtype=block_dtype)
        return _QuantizedKVProbe(exact, pool)

    engines = {
        "decode.int8": DecodeEngine(params, cfg, max_seq=max_seq,
                                    dtype="int8"),
        "decode.bf16": DecodeEngine(params, cfg, max_seq=max_seq,
                                    dtype=jnp.bfloat16),
        "kv.int8": kv_probe("int8"),
        "kv.fp8": (kv_probe("fp8") if kv_quant.fp8_supported()
                   else "backend lacks float8_e4m3fn storage "
                        "(ops.kv_quant.fp8_supported() is False)"),
    }
    oracle = ToleranceOracle(seed)
    rows = []
    for path in sorted(TOLERANCE_POLICY):
        if path not in engines:
            # a declared budget with no measuring engine here is a
            # WIRING gap, not a tolerance breach — keep the two
            # distinguishable in the bench journal (the row's error
            # names the unmapped path instead of a bare KeyError)
            raise GraftnumError(
                f"TOLERANCE_POLICY declares {path!r} but oracle_rows "
                f"builds no engine for it (covered: {sorted(engines)})"
                " — wire the new path's approximate engine in before "
                "declaring its budget", path=path)
        if isinstance(engines[path], str):
            rows.append({"path": path, "seed": seed,
                         "skipped": engines[path]})
            continue
        report = oracle.compare(path, engines[path], exact)
        rows.append({k: v for k, v in report.items() if k != "positions"})
    return rows
