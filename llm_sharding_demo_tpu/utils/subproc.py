"""Watchdogged child processes with output-tail hygiene.

The round driver captures the TAIL of bench/dryrun output; XLA's AOT
cache loader logs a multi-KB machine-feature diff at ERROR level per
cache hit (``TF_CPP_MIN_LOG_LEVEL`` does not reliably silence it), so a
child's combined output streams through a line filter before reaching
stdout. A kill timer enforces the wall-clock budget (blocking readline
cannot time out by itself), and parent-side stream failures kill the
child so it can never orphan-block on a full pipe. Shared by
``__graft_entry__`` (dryrun bootstrap) and ``bench.py`` (matrix child).
"""

from __future__ import annotations

import subprocess
import threading
from typing import Optional, Sequence

AOT_SPEW_MARKERS = ("cpu_aot_loader", "machine feature")


def run_filtered(cmd: Sequence[str], *, env: Optional[dict] = None,
                 cwd: Optional[str] = None, timeout_s: float,
                 drop: Sequence[str] = AOT_SPEW_MARKERS) -> int:
    """Run ``cmd`` streaming its combined output to stdout minus lines
    containing any ``drop`` marker. Returns the exit code; raises
    ``TimeoutError`` when the watchdog killed the child."""
    proc = subprocess.Popen(list(cmd), env=env, cwd=cwd,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            errors="replace")
    timer = threading.Timer(timeout_s, proc.kill)
    timer.start()
    try:
        assert proc.stdout is not None
        for line in proc.stdout:
            if any(marker in line for marker in drop):
                continue
            print(line, end="", flush=True)
        rc = proc.wait()
    except BaseException:
        # parent-side failure mid-stream (SIGINT, encoding, broken
        # pipe): never orphan a child that would block on a full pipe
        # with no watchdog left
        proc.kill()
        raise
    finally:
        expired = not timer.is_alive()
        timer.cancel()
    if rc != 0 and expired:  # a clean exit racing the timer lands below
        raise TimeoutError(f"child exceeded the {timeout_s:g}s watchdog")
    return rc
