"""Watchdogged child processes with output-tail hygiene.

The round driver captures the TAIL of bench/dryrun output; XLA's AOT
cache loader logs a multi-KB machine-feature diff at ERROR level per
cache hit (``TF_CPP_MIN_LOG_LEVEL`` does not reliably silence it), so a
child's combined output streams through a line filter before reaching
stdout. A kill timer enforces the wall-clock budget (blocking readline
cannot time out by itself), and parent-side stream failures kill the
child so it can never orphan-block on a full pipe. Shared by
``__graft_entry__`` (dryrun bootstrap) and ``bench.py`` (matrix child).
"""

from __future__ import annotations

import subprocess
import threading
from typing import Optional, Sequence

AOT_SPEW_MARKERS = ("cpu_aot_loader", "machine feature")

# Fault contract (tools/graftcheck faults pass): ``proc.wait()`` is
# timeout-less ON PURPOSE — the kill timer is the deadline authority (a
# blocking readline cannot time out by itself), and a watchdog kill
# surfaces as TimeoutError with the killed flag disambiguating it from
# the child's own exit.
FAULT_POLICY = {
    "proc.wait": ("watchdog", "none",
                  "kill timer bounds the child; TimeoutError on kill"),
}


def run_filtered(cmd: Sequence[str], *, env: Optional[dict] = None,
                 cwd: Optional[str] = None, timeout_s: float,
                 drop: Sequence[str] = AOT_SPEW_MARKERS) -> int:
    """Run ``cmd`` streaming its combined output to stdout minus lines
    containing any ``drop`` marker. Returns the exit code; raises
    ``TimeoutError`` when the watchdog killed the child."""
    proc = subprocess.Popen(list(cmd), env=env, cwd=cwd,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            errors="replace")
    # The callback sets ``killed`` BEFORE the kill, and TimeoutError is
    # raised only when the flag is set: a child that exited nonzero on
    # its own just as the timer fired (timer dead, but it never killed
    # anything) reports its real failure code instead of being
    # misattributed to the watchdog. ``timer.is_alive()`` alone cannot
    # distinguish the two — the test pins the race.
    killed = threading.Event()

    def _watchdog_kill():
        if proc.poll() is None:   # only a LIVE child can be watchdog-
            killed.set()          # killed: a child that already exited
            proc.kill()           # on its own keeps its real rc even
                                  # when the timer fires before cancel()

    timer = threading.Timer(timeout_s, _watchdog_kill)
    timer.start()
    try:
        assert proc.stdout is not None
        for line in proc.stdout:
            if any(marker in line for marker in drop):
                continue
            print(line, end="", flush=True)
        rc = proc.wait()
    except BaseException:
        # parent-side failure mid-stream (SIGINT, encoding, broken
        # pipe): never orphan a child that would block on a full pipe
        # with no watchdog left
        proc.kill()
        raise
    finally:
        timer.cancel()
    if rc != 0 and killed.is_set():
        raise TimeoutError(f"child exceeded the {timeout_s:g}s watchdog")
    return rc
