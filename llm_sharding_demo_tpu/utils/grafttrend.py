"""grafttrend: streaming telemetry reducer + declared burn-rate/drift watches.

The dynamic half of the graftcheck trend pass (``tools/graftcheck/
trend.py`` is the static half — the same static+dynamic split as
graftsan/graftlock/graftload/graftwatch/graftmem/graftshard, applied at
the TREND level). The spine *produces* rich telemetry — graftscope
occupancy series, graftmem ledger drift, the SLO source histograms,
breaker gauges, grafttime events — and until now consumed it passively:
``costmodel.calibrate`` read journals only at startup, graftwatch
routed but did not size, and black-box dumps fired only on typed
failures. An SLO burn or a measured-vs-modeled byte drift was invisible
until a bench run. This module closes that loop in-process.

**The reducer** (:class:`TrendReducer`): a bounded, lock-disciplined
streaming fold over the existing producers. Samples enter either
through :meth:`TrendReducer.observe` (the seeded/test path — a pure
``(series, value, weight, t_ms)`` record) or :meth:`TrendReducer.poll`
(the live tap: registry histogram buckets behind loadgen's
``SLO_SOURCE_METRICS``, the deadline-miss/request counter pair, the
``queue_depth``/``hop_breaker_open`` gauges, and graftmem
``reconcile`` drift when a plan row is supplied). Every series keeps a
bounded window of ``(t_ms, value, weight)`` points; reductions
(windowed rate, p50/p99 sketch over the bounded window, EWMA drift)
are pure functions of the stored samples and the evaluation instant.

**The declared contract**: ``WATCH_POLICY = {watch: (series, window,
threshold, severity)}`` — a dict literal the static trend pass scans,
exactly like ``SLO_POLICY``/``FAULT_POLICY``/``GUARDED_STATE``. Three
watch modes, classified by the series (``watch_mode``):

- **burn** (SLO source series): multi-window burn-rate. ``window`` is
  ``(short_ms, long_ms)``; the burn rate in a window is the violating
  fraction divided by the declared error budget (the loosest
  ``SLO_POLICY`` target/percentile for that series — a burn against
  the loosest declared promise is a burn under every declared
  promise), and the watch trips only when BOTH windows burn past
  ``threshold`` (the SRE multi-window rule: the short window makes the
  alert fast, the long window keeps a blip from paging).
- **drift** (derived measured-vs-modeled series, ``DERIVED_SERIES``):
  EWMA of the drift values inside ``window`` against ``threshold``.
- **level** (catalog gauges): windowed mean against ``threshold``.

**Alerting**: a trip emits a typed ``trend_alert`` event on the
grafttime bus, increments ``trend_alerts_total{watch,severity}``, and
triggers a grafttime black-box dump — the events that LED to the trip
outlive the ring. Trips LATCH per watch: a sustained burn alerts
exactly once until the watch evaluates clean again (hysteresis — the
seeded fixtures pin exactly one alert per episode). Alert evaluation
is replay-identical: the alert record minus its wall-clock field is a
pure function of the observed samples and the evaluation instant, so
two seeded GRAFTSCHED runs serialize byte-identically
(:meth:`TrendReducer.alerts` with ``strip_time=True``).

**The refit loop** (:func:`refit`): re-fits the cost-model byte
weights from the LIVE graftscope attribution rings — the exact
least-squares ``graftwatch.fit_cost_weights`` runs over journal rows,
fed by :func:`live_attribution_journal` instead of a startup file —
publishes the fitted weight as the ``costmodel_byte_weight`` gauge,
and threads it into the switcher's scoring between waves
(``PlanSwitcher.set_weights``). The PR 11 golden is preserved by
construction: ``score_plans`` is linear in the ICI weight, so a weight
change shifts every plan score by exactly ``(w' - w) * comm_bytes``,
and weights are pure scoring inputs — a refit can never mint a
compiled program, so plan switches stay inside the pre-certified
zero-recompile envelope.

Everything is served at ``GET /debug/trend`` (+ the ``/healthz``
``trend`` block) by serving/app.py.
"""

from __future__ import annotations

import bisect
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import graftsched, graftscope, grafttime

# Lock-discipline contract (tools/graftcheck locks pass): the sample
# windows, alert ring, per-watch latches, poll cursors, and refit
# journal are touched from arbitrary handler/poller threads; all live
# under the owning reducer's ``_lock``. External producers (registry,
# graftscope, graftmem) are read BEFORE the hold is taken — the
# reducer's lock never nests inside or around a foreign lock.
GUARDED_STATE = {"_samples": "_lock", "_alerts": "_lock",
                 "_latched": "_lock", "_evals": "_lock",
                 "_cursors": "_lock", "_refits": "_lock"}
LOCK_ORDER = ("_lock",)

# Timeline contract (tools/graftcheck timeline pass): every watch trip
# lands on the unified causal stream, so the telemetry that provoked an
# alert is visible on the same clock as the alert itself.
TIMELINE_EVENTS = {
    "trend_alert": "TrendReducer.evaluate",
}

# The fixed severity vocabulary (the trend pass rejects anything else):
# "page" wakes a human, "ticket" files work.
SEVERITIES = ("page", "ticket")

# Derived series: trend inputs that are COMPUTED from producer pairs
# rather than emitted as catalog metrics. Each entry documents its
# provenance; the trend pass's watch-without-source rule accepts a
# watch on a derived series only when it is declared here (and flags a
# derived series no watch consumes — a dead declaration).
DERIVED_SERIES = {
    "graftmem_params_drift":
        "graftmem.reconcile components.params drift — |measured/"
        "predicted - 1| of live ledger param bytes vs the cost model's "
        "aval arithmetic (fed by TrendReducer.poll(plan_row=...))",
    "graftmem_kv_drift":
        "graftmem.reconcile components.kv drift — |measured/predicted "
        "- 1| of live pool/cache bytes vs the planned KV footprint "
        "(fed by TrendReducer.poll(plan_row=...))",
    "costmodel_weight_drift":
        "|fitted ici_byte_weight / a-priori ICI_BYTE_WEIGHT - 1| — how "
        "far the live refit has moved the cost model off its prior "
        "(fed by grafttrend.refit)",
}

# THE declared watch contract: {watch: (series, window, threshold,
# severity)}. ``series`` is a METRIC_CATALOG name or a DERIVED_SERIES
# key; ``window`` is (short_ms, long_ms) for burn watches and a single
# window_ms for drift/level; ``threshold`` is the burn multiple /
# drift bound / level bound; ``severity`` is from SEVERITIES. The
# static trend pass verifies every SLO_POLICY metric's source series
# is covered by a live watch (slo-without-watch), every watch names a
# known+emitted series (watch-without-source), and every entry is
# well-formed (malformed-watch); an empty policy fails --strict as
# vacuous. Thresholds are against the tiny CPU test model and
# deliberately loose — the contract is the SHAPE (which series, which
# windows); tightening per deployment is a config edit.
WATCH_POLICY = {
    # multi-window SLO burn-rate watches, one per SLO source series
    # (loadgen.profiles.SLO_SOURCE_METRICS): trip when the violating
    # fraction burns the declared error budget at >= threshold x in
    # BOTH windows
    "slo_ttft_burn": ("ttft_seconds", (10_000.0, 60_000.0), 2.0,
                      "page"),
    "slo_tpot_burn": ("tpot_seconds", (10_000.0, 60_000.0), 2.0,
                      "page"),
    "slo_e2e_burn": ("generate_request_seconds",
                     (10_000.0, 60_000.0), 2.0, "page"),
    "slo_deadline_burn": ("deadline_misses_total",
                          (10_000.0, 60_000.0), 2.0, "page"),
    # measured-vs-modeled relative-drift watches over the graftmem
    # reconcile pairs (bench_diff gates the same drift lower-better in
    # the hbm_attribution row — this is the live, between-bench watch)
    "hbm_params_drift": ("graftmem_params_drift", 60_000.0, 0.10,
                         "ticket"),
    "hbm_kv_drift": ("graftmem_kv_drift", 60_000.0, 0.25, "ticket"),
    # the refit loop watching itself: a fitted weight far off the
    # a-priori prior means the host's byte economics moved (or the
    # attribution inputs went bad) — either way a human should look
    "cost_weight_drift": ("costmodel_weight_drift", 300_000.0, 0.50,
                          "ticket"),
    # level watches over live-state gauges: a breaker that stays open
    # across the window, and a queue holding deeper than the declared
    # surge bound
    "breaker_stuck_open": ("hop_breaker_open", 30_000.0, 0.5, "page"),
    "queue_depth_surge": ("queue_depth", 30_000.0, 16.0, "ticket"),
}

# Declared sizing contract (the ROADMAP item-7 "routes but doesn't
# size" follow-on): {knob: (source_series, min_scale, max_scale)}.
# Between waves the switcher reads the reducer's windowed occupancy
# estimate for the source series and scales the knob's BASE value by
# estimate/capacity, clamped to [min_scale, max_scale] x base. Both
# knobs are pure scheduling parameters — neither keys a compiled
# program (zero-recompile by construction) nor changes any emitted
# token (greedy decode is batch-wait independent; the byte-equality
# pin in tests/test_grafttrend.py holds sized == unsized per request).
SIZING_POLICY = {
    "batch_wait_ms": ("queue_depth", 0.5, 4.0),
    "queue_limit": ("queue_depth", 1.0, 4.0),
}

# bounded state: a ring, never a log
SAMPLE_CAPACITY = 1024      # points per series
ALERT_CAPACITY = 128        # alert journal
REFIT_CAPACITY = 16         # refit journal
# EWMA smoothing for drift watches (deterministic: folded over the
# windowed samples in t_ms order)
DRIFT_ALPHA = 0.3


class WatchPolicyError(ValueError):
    """A malformed watch declaration reached the reducer — the dynamic
    half of the trend pass's malformed-watch rule."""


def watch_mode(series: str) -> str:
    """'burn' | 'drift' | 'level' for a watched series. SLO source
    series get multi-window burn-rate, declared derived series get
    EWMA drift, everything else (catalog gauges) gets a windowed-level
    check."""
    from ..loadgen import profiles
    if series in profiles.SLO_SOURCE_METRICS.values():
        return "burn"
    if series in DERIVED_SERIES:
        return "drift"
    return "level"


def slo_budget(series: str) -> Tuple[float, float]:
    """``(target, budget_fraction)`` for an SLO source series — the
    LOOSEST declared target across SLO_POLICY profiles (the reducer is
    profile-agnostic: a sample stream mixes profiles, and a burn
    against the loosest declared promise is a burn under every
    declared promise) and the matching error budget (1 - pct/100 for
    percentile targets; the declared miss-fraction cap itself for
    ``deadline_miss``, whose percentile slot is fixed at 100)."""
    from ..loadgen import profiles
    metric = {v: k for k, v in profiles.SLO_SOURCE_METRICS.items()
              }.get(series)
    if metric is None:
        raise WatchPolicyError(
            f"{series!r} is not an SLO source series; burn watches "
            f"cover {sorted(profiles.SLO_SOURCE_METRICS.values())}")
    targets: List[float] = []
    budgets: List[float] = []
    for policy in profiles.SLO_POLICY.values():
        if metric in policy:
            target, pct = policy[metric]
            targets.append(float(target))
            budgets.append(float(target) if pct >= 100
                           else 1.0 - pct / 100.0)
    if not targets:
        raise WatchPolicyError(
            f"no SLO_POLICY profile declares metric {metric!r} — a "
            "burn watch needs a declared budget to burn")
    return max(targets), max(budgets)


def validate_policy(policy: Dict[str, tuple]) -> None:
    """Typed validation of a WATCH_POLICY dict (the reducer refuses a
    malformed contract at construction; the static pass catches the
    same shapes compile-free)."""
    if not isinstance(policy, dict) or not policy:
        raise WatchPolicyError("WATCH_POLICY must be a non-empty dict "
                               "{watch: (series, window, threshold, "
                               "severity)}")
    for watch, entry in policy.items():
        if not (isinstance(entry, tuple) and len(entry) == 4):
            raise WatchPolicyError(
                f"watch {watch!r}: entry must be a 4-tuple (series, "
                f"window, threshold, severity), got {entry!r}")
        series, window, threshold, severity = entry
        if not isinstance(series, str) or not series:
            raise WatchPolicyError(
                f"watch {watch!r}: series must be a non-empty string")
        if severity not in SEVERITIES:
            raise WatchPolicyError(
                f"watch {watch!r}: severity {severity!r} outside "
                f"{SEVERITIES}")
        if not (isinstance(threshold, (int, float))
                and not isinstance(threshold, bool) and threshold > 0):
            raise WatchPolicyError(
                f"watch {watch!r}: threshold must be a positive number")
        windows = window if isinstance(window, tuple) else (window,)
        if not windows or not all(
                isinstance(w, (int, float)) and not isinstance(w, bool)
                and w > 0 for w in windows):
            raise WatchPolicyError(
                f"watch {watch!r}: window must be a positive ms value "
                "or a (short_ms, long_ms) tuple")
        if watch_mode(series) == "burn":
            if len(windows) != 2 or windows[0] >= windows[1]:
                raise WatchPolicyError(
                    f"watch {watch!r}: burn watches need (short_ms, "
                    f"long_ms) with short < long, got {window!r}")
            slo_budget(series)   # must have a declared budget to burn
        elif len(windows) != 1:
            raise WatchPolicyError(
                f"watch {watch!r}: {watch_mode(series)} watches take a "
                f"single window_ms, got {window!r}")


# -- pure windowed reductions -------------------------------------------------


def _windowed(samples: List[tuple], now_ms: float,
              window_ms: float) -> List[tuple]:
    return [s for s in samples if now_ms - s[0] <= window_ms]


def burn_rate(samples: List[tuple], now_ms: float, window_ms: float,
              budget: float) -> Optional[float]:
    """Violating weight over total weight, divided by the error budget
    — None when the window carries no weight (insufficient data is not
    a clean bill, it is silence)."""
    win = _windowed(samples, now_ms, window_ms)
    total = sum(s[2] for s in win)
    if total <= 0:
        return None
    return (sum(s[1] for s in win) / total) / budget


def windowed_mean(samples: List[tuple], now_ms: float,
                  window_ms: float) -> Optional[float]:
    win = _windowed(samples, now_ms, window_ms)
    if not win:
        return None
    return sum(s[1] for s in win) / len(win)


def ewma_drift(samples: List[tuple], now_ms: float, window_ms: float,
               alpha: float = DRIFT_ALPHA) -> Optional[float]:
    """EWMA of the drift values inside the window, folded in ``t_ms``
    order (append order inside one series is t_ms order; the fold is a
    pure function of the windowed values, so seeded runs replay it)."""
    win = _windowed(samples, now_ms, window_ms)
    if not win:
        return None
    acc = win[0][1]
    for _, value, _ in win[1:]:
        acc = alpha * value + (1.0 - alpha) * acc
    return acc


def percentile_sketch(samples: List[tuple], now_ms: float,
                      window_ms: float) -> dict:
    """Exact p50/p99 over the bounded window (a sketch in the sense
    that the window itself is bounded — old points rotated out of the
    ring are honestly gone, not approximated)."""
    vals = sorted(s[1] for s in _windowed(samples, now_ms, window_ms))
    if not vals:
        return {"points": 0}
    return {
        "points": len(vals),
        "p50": round(vals[(len(vals) - 1) // 2], 6),
        "p99": round(vals[min(len(vals) - 1,
                              (len(vals) * 99) // 100)], 6),
        "last": round(vals[-1] if len(vals) == 1
                      else samples[-1][1], 6),
    }


# -- the reducer --------------------------------------------------------------


class TrendReducer:
    """Bounded streaming reducer + watch evaluator. One instance per
    serving app (module-level :data:`REDUCER` is the process default,
    the graftscope/grafttime pattern)."""

    def __init__(self, policy: Optional[Dict[str, tuple]] = None,
                 registry=None, blackbox: bool = True,
                 min_weight: float = 4.0, min_points: int = 3):
        from .metrics import REGISTRY
        self.registry = registry if registry is not None else REGISTRY
        self.policy = dict(policy if policy is not None
                           else WATCH_POLICY)
        validate_policy(self.policy)
        self.blackbox = blackbox
        # evaluation floors: a burn verdict needs this much windowed
        # weight in the SHORT window, drift/level this many points —
        # below the floor the watch reports "insufficient", never trips
        self.min_weight = float(min_weight)
        self.min_points = int(min_points)
        self._lock = graftsched.lock("grafttrend.TrendReducer._lock")
        self._samples: Dict[str, deque] = {}
        self._alerts: deque = deque(maxlen=ALERT_CAPACITY)
        self._latched: Dict[str, bool] = {}
        self._evals = 0
        self._cursors: Dict[str, object] = {}
        self._refits: deque = deque(maxlen=REFIT_CAPACITY)

    # -- ingestion --

    def observe(self, series: str, value: float, weight: float = 1.0,
                t_ms: Optional[float] = None) -> None:
        """Record one sample: ``value`` is the series' payload
        (violating count for burn series, drift for derived series,
        gauge level otherwise), ``weight`` the denominator weight
        (total count for burn series; 1 elsewhere). ``t_ms`` defaults
        to the grafttime bus clock — seeded fixtures pass explicit
        instants so evaluation replays identically."""
        t = grafttime.now_ms() if t_ms is None else float(t_ms)
        with self._lock:
            ring = self._samples.get(series)
            if ring is None:
                ring = self._samples[series] = deque(
                    maxlen=SAMPLE_CAPACITY)
            ring.append((t, float(value), float(weight)))

    def poll(self, plan_row=None, now_ms: Optional[float] = None) -> int:
        """The live tap: fold the in-process producers into samples.
        Reads registry histogram-bucket deltas for the SLO latency
        series (violating = new observations in buckets past the
        loosest declared target), the deadline-miss/request counter
        pair, the watched catalog gauges, and — when ``plan_row`` (a
        ``costmodel.PlanRow`` or dict) is supplied and the graftmem
        ledger is live — the reconcile drift pair. Returns the number
        of samples ingested. All producer reads happen BEFORE the
        reducer's hold (lock discipline: no foreign lock nests inside
        ``_lock``)."""
        from .metrics import DEFAULT_BUCKETS, METRIC_CATALOG
        t = grafttime.now_ms() if now_ms is None else float(now_ms)
        watched = {entry[0] for entry in self.policy.values()}

        # gather phase (no reducer hold): registry + graftmem reads
        buckets = self.registry.histogram_buckets()
        flat = self.registry.snapshot()
        gathered: List[Tuple[str, float, float]] = []

        def _counter_total(name: str) -> float:
            return sum(v for key, v in flat.items()
                       if key == name or key.startswith(name + "{"))

        hist_cursors: Dict[str, Tuple[float, float]] = {}
        for series in sorted(watched):
            if watch_mode(series) != "burn":
                continue
            if METRIC_CATALOG.get(series) == "histogram":
                target, _ = slo_budget(series)
                total = 0.0
                viol = 0.0
                # bucket i spans (bounds[i-1], bounds[i]]; a bucket
                # whose LOWER edge is >= target holds only violations
                # (conservative: the target's own bucket is not charged)
                cut = bisect.bisect_left(DEFAULT_BUCKETS, target) + 1
                for key, (counts, _s, _n) in buckets.items():
                    if key != series and not key.startswith(
                            series + "{"):
                        continue
                    total += sum(counts)
                    viol += sum(counts[cut:])
                hist_cursors[series] = (total, viol)
            else:
                # the deadline-miss counter burns against the request
                # counter: one sample per poll carrying the interval's
                # (misses, requests) delta pair
                hist_cursors[series] = (
                    _counter_total("generate_requests_total"),
                    _counter_total(series))
        gauge_levels: Dict[str, float] = {}
        for series in sorted(watched):
            if watch_mode(series) == "level" \
                    and METRIC_CATALOG.get(series) == "gauge":
                vals = [v for key, v in flat.items()
                        if key == series
                        or key.startswith(series + "{")]
                if vals:
                    # max over label sets: any open breaker / the
                    # deepest queue is the signal
                    gauge_levels[series] = max(vals)
        drift_pair: Dict[str, float] = {}
        if plan_row is not None:
            from . import graftmem
            rec = graftmem.reconcile(plan_row)
            for comp, series in (("params", "graftmem_params_drift"),
                                 ("kv", "graftmem_kv_drift")):
                if series not in watched:
                    continue
                d = rec["components"].get(comp, {}).get("drift")
                if d is not None:
                    drift_pair[series] = float(d)

        # fold phase (one hold): diff cursors, append samples
        with self._lock:
            for series, cur in hist_cursors.items():
                prev = self._cursors.get(series)
                self._cursors[series] = cur
                if prev is None:
                    continue           # first poll seeds the cursor
                d_total = cur[0] - prev[0]
                d_viol = cur[1] - prev[1]
                if d_total <= 0:
                    continue
                ring = self._samples.get(series)
                if ring is None:
                    ring = self._samples[series] = deque(
                        maxlen=SAMPLE_CAPACITY)
                ring.append((t, max(d_viol, 0.0), d_total))
                gathered.append((series, d_viol, d_total))
            for series, level in gauge_levels.items():
                ring = self._samples.get(series)
                if ring is None:
                    ring = self._samples[series] = deque(
                        maxlen=SAMPLE_CAPACITY)
                ring.append((t, level, 1.0))
                gathered.append((series, level, 1.0))
            for series, d in drift_pair.items():
                ring = self._samples.get(series)
                if ring is None:
                    ring = self._samples[series] = deque(
                        maxlen=SAMPLE_CAPACITY)
                ring.append((t, d, 1.0))
                gathered.append((series, d, 1.0))
        return len(gathered)

    # -- evaluation --

    def _verdict(self, mode: str, series: str, samples: List[tuple],
                 now: float, window, threshold: float):
        """(tripped, value, window_ms) or None for insufficient data.
        Pure over its inputs — the replay-identity contract."""
        if mode == "burn":
            short_ms, long_ms = window
            _, budget = slo_budget(series)
            win = _windowed(samples, now, short_ms)
            if sum(s[2] for s in win) < self.min_weight:
                return None
            short = burn_rate(samples, now, short_ms, budget)
            long = burn_rate(samples, now, long_ms, budget)
            if short is None or long is None:
                return None
            return (short > threshold and long > threshold,
                    min(short, long), window)
        win_ms = window if not isinstance(window, tuple) else window[0]
        win = _windowed(samples, now, win_ms)
        if len(win) < self.min_points:
            return None
        if mode == "drift":
            value = ewma_drift(samples, now, win_ms)
        else:
            value = windowed_mean(samples, now, win_ms)
        if value is None:
            return None
        return value > threshold, value, win_ms

    def evaluate(self, now_ms: Optional[float] = None) -> List[dict]:
        """Evaluate every declared watch; returns the NEW trips (the
        latched episodes). The loop body is a pure function of the
        sample windows + ``now_ms`` — seeded inputs replay to the same
        alerts — and all emission (timeline event, metric, black-box
        dump) happens OUTSIDE the hold."""
        now = grafttime.now_ms() if now_ms is None else float(now_ms)
        trips: List[dict] = []
        with self._lock:
            self._evals += 1
            for watch in sorted(self.policy):
                series, window, threshold, severity = self.policy[watch]
                samples = list(self._samples.get(series, ()))
                mode = watch_mode(series)
                v = self._verdict(mode, series, samples, now, window,
                                  threshold)
                if v is None or not v[0]:
                    # clean (or silent) evaluation ends the episode:
                    # the next trip alerts again
                    self._latched.pop(watch, None)
                    continue
                if self._latched.get(watch):
                    continue          # already alerted this episode
                self._latched[watch] = True
                alert = {
                    "watch": watch,
                    "series": series,
                    "severity": severity,
                    "mode": mode,
                    "window_ms": (list(window)
                                  if isinstance(window, tuple)
                                  else window),
                    "value": round(v[1], 6),
                    "threshold": threshold,
                    # wall-clock context only — replay identity is over
                    # the alert MINUS this field (alerts(strip_time=True))
                    "t_ms": round(now, 3),
                }
                self._alerts.append(alert)
                trips.append(alert)
        for alert in trips:
            grafttime.emit("trend_alert", watch=alert["watch"],
                           severity=alert["severity"],
                           series=alert["series"],
                           mode=alert["mode"],
                           value=alert["value"],
                           threshold=alert["threshold"])
            self.registry.inc("trend_alerts_total",
                              watch=alert["watch"],
                              severity=alert["severity"])
            if self.blackbox:
                grafttime.blackbox(f"trend_alert:{alert['watch']}")
        return trips

    # -- sizing input (graftwatch's between-waves hook) --

    def occupancy_estimate(self, series: str = "queue_depth",
                           window_ms: float = 30_000.0,
                           now_ms: Optional[float] = None
                           ) -> Optional[float]:
        """Windowed mean of an occupancy series — what
        ``PlanSwitcher.resize_from_trend`` scales the SIZING_POLICY
        knobs from. None when the window is empty (the sizer then
        leaves the knob at its base: silence never resizes)."""
        now = grafttime.now_ms() if now_ms is None else float(now_ms)
        with self._lock:
            samples = list(self._samples.get(series, ()))
        return windowed_mean(samples, now, window_ms)

    # -- observability --

    def alerts(self, n: Optional[int] = None,
               strip_time: bool = False) -> List[dict]:
        """The bounded alert journal (oldest first).
        ``strip_time=True`` drops the wall-clock field — what the
        replay-identity pins compare."""
        with self._lock:
            rows = list(self._alerts)
        if n is not None:
            rows = rows[-n:]
        if strip_time:
            rows = [{k: v for k, v in r.items() if k != "t_ms"}
                    for r in rows]
        return rows

    def refit_history(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._refits]

    def note_refit(self, row: dict) -> None:
        with self._lock:
            self._refits.append(dict(row))

    def describe(self, now_ms: Optional[float] = None) -> dict:
        """The GET /debug/trend payload body: per-watch state (mode,
        window, threshold, latest value, latched), per-series windowed
        reductions (rate, p50/p99 sketch), the alert journal, and the
        declared contracts."""
        now = grafttime.now_ms() if now_ms is None else float(now_ms)
        with self._lock:
            samples = {s: list(ring)
                       for s, ring in self._samples.items()}
            latched = dict(self._latched)
            evals = self._evals
            alerts = list(self._alerts)
            refits = [dict(r) for r in self._refits]
        watches = {}
        for watch in sorted(self.policy):
            series, window, threshold, severity = self.policy[watch]
            mode = watch_mode(series)
            v = self._verdict(mode, series,
                              samples.get(series, []), now, window,
                              threshold)
            watches[watch] = {
                "series": series,
                "mode": mode,
                "window_ms": (list(window) if isinstance(window, tuple)
                              else window),
                "threshold": threshold,
                "severity": severity,
                "state": ("insufficient" if v is None
                          else "tripped" if v[0] else "ok"),
                "value": None if v is None else round(v[1], 6),
                "latched": bool(latched.get(watch)),
            }
        series_view = {}
        for series, pts in sorted(samples.items()):
            win_ms = max(
                (max(w[1]) if isinstance(w[1], tuple) else w[1])
                for w in self.policy.values() if w[0] == series
            ) if any(w[0] == series for w in self.policy.values()) \
                else 60_000.0
            win = _windowed(pts, now, win_ms)
            series_view[series] = {
                "points": len(pts),
                "window_points": len(win),
                "rate_per_s": round(
                    sum(s[2] for s in win) / (win_ms / 1e3), 6),
                "sketch": percentile_sketch(pts, now, win_ms),
            }
        return {
            "now_ms": round(now, 3),
            "evaluations": evals,
            "watches": watches,
            "series": series_view,
            "alerts": alerts,
            "refits": refits,
            "policy": {w: {"series": e[0],
                           "window_ms": (list(e[1])
                                         if isinstance(e[1], tuple)
                                         else e[1]),
                           "threshold": e[2], "severity": e[3]}
                       for w, e in sorted(self.policy.items())},
            "sizing": {k: {"source": v[0], "min_scale": v[1],
                           "max_scale": v[2]}
                       for k, v in sorted(SIZING_POLICY.items())},
            "derived_series": dict(DERIVED_SERIES),
        }

    def health_view(self) -> dict:
        """The /healthz ``trend`` block: watch count, live trip state,
        alert totals — small enough for a probe, loud enough that a
        latched page is visible without the debug surface."""
        with self._lock:
            latched = sorted(w for w, on in self._latched.items()
                             if on)
            alerts = len(self._alerts)
            evals = self._evals
        return {"watches": len(self.policy),
                "evaluations": evals,
                "alerts_journaled": alerts,
                "latched": latched}

    # -- test isolation (tests/conftest.py) --

    def dump_state(self) -> tuple:
        with self._lock:
            return ({s: list(r) for s, r in self._samples.items()},
                    list(self._alerts), dict(self._latched),
                    self._evals, dict(self._cursors),
                    list(self._refits))

    def restore_state(self, state: tuple) -> None:
        samples, alerts, latched, evals, cursors, refits = state
        with self._lock:
            self._samples = {s: deque(r, maxlen=SAMPLE_CAPACITY)
                             for s, r in samples.items()}
            self._alerts = deque(alerts, maxlen=ALERT_CAPACITY)
            self._latched = dict(latched)
            self._evals = evals
            self._cursors = dict(cursors)
            self._refits = deque(refits, maxlen=REFIT_CAPACITY)

    def clear(self) -> None:
        with self._lock:
            self._samples = {}
            self._alerts = deque(maxlen=ALERT_CAPACITY)
            self._latched = {}
            self._evals = 0
            self._cursors = {}
            self._refits = deque(maxlen=REFIT_CAPACITY)


# process-wide default reducer (what serving.app uses; tests
# snapshot/restore it via the conftest fixture)
REDUCER = TrendReducer()


# -- the live refit loop ------------------------------------------------------


def live_attribution_journal(costs=None) -> dict:
    """Assemble a ``graftscope_attribution``-shaped journal from the
    LIVE graftscope dispatch rings — the in-process analog of the
    startup bench journal ``graftwatch.fit_cost_weights`` was built
    for. Each profiled scope with recorded dispatches contributes its
    measured seconds; the modeled byte terms come from the switcher's
    static plan costs (``costs`` — a ``{label: PlanCost}`` map). With
    no dispatches or no costs the journal carries no workload rows and
    the fit honestly falls back to the a-priori weights
    (``rows_used == 0``), never a fabricated number."""
    snap = graftscope.snapshot(n=0)
    workloads: List[dict] = []
    dispatch = snap.get("dispatch") or {}
    if costs:
        from tools.graftcheck.costmodel import ICI_BYTE_WEIGHT
        total_secs = 0.0
        total_calls = 0
        entry_points: Dict[str, dict] = {}
        for scope, ring in sorted(dispatch.items()):
            secs = float(ring.get("seconds_total", 0.0) or 0.0)
            calls = int(ring.get("calls", 0) or 0)
            if calls <= 0 or secs <= 0:
                continue
            total_secs += secs
            total_calls += calls
            entry_points[scope] = {"seconds_total": round(secs, 6),
                                   "calls": calls}
        if total_calls > 0:
            measured = total_secs / total_calls
            for label, pc in sorted(costs.items()):
                cost = pc.to_dict() if hasattr(pc, "to_dict") \
                    else dict(pc)
                comm = float(cost.get("comm_bytes", 0) or 0)
                # the same scored total the planner ranks on: static
                # byte terms with comm priced at the a-priori weight
                # (fit_cost_weights removes that weighting again)
                modeled = (float(cost.get("param_bytes", 0))
                           + float(cost.get("kv_bytes_per_row", 0))
                           + float(cost.get("paged_overhead", 0))
                           + ICI_BYTE_WEIGHT * comm)
                if modeled <= 0:
                    continue
                workloads.append({
                    "workload": f"live_{label}",
                    "measured_decode_seconds_per_token": measured,
                    "modeled_cost_bytes_per_token": modeled,
                    "modeled_comm_bytes_per_token": comm,
                    "entry_points": entry_points,
                })
    return {"name": "graftscope_attribution",
            "source": "grafttrend.live_attribution_journal",
            "workloads": workloads}


def refit(journal=None, switcher=None, registry=None,
          reducer: Optional[TrendReducer] = None):
    """Re-fit the cost-model byte weights live and thread them into
    plan scoring. ``journal`` defaults to
    :func:`live_attribution_journal` over the current graftscope rings
    (using ``switcher.costs`` for the modeled terms); the fit itself
    is ``graftwatch.fit_cost_weights`` — the SAME least-squares the
    startup journal path runs, on live inputs. Publishes the resolved
    ICI weight as the ``costmodel_byte_weight`` gauge (+ occupancy
    series), feeds the ``costmodel_weight_drift`` derived series, and
    installs the weights on ``switcher`` between waves
    (``PlanSwitcher.set_weights`` — scoring-only: linear in the
    weight, zero recompiles by construction). Returns the fitted
    ``CostWeights``."""
    from . import graftwatch
    from .metrics import REGISTRY
    from tools.graftcheck.costmodel import ICI_BYTE_WEIGHT
    if journal is None:
        journal = live_attribution_journal(
            getattr(switcher, "costs", None))
    weights = graftwatch.fit_cost_weights(journal)
    w = weights.ici_byte_weight
    if not w:
        w = ICI_BYTE_WEIGHT
    reg = registry if registry is not None else REGISTRY
    reg.gauge("costmodel_byte_weight", float(w))
    graftscope.sample("costmodel_byte_weight", float(w))
    red = reducer if reducer is not None else REDUCER
    red.observe("costmodel_weight_drift",
                abs(float(w) / ICI_BYTE_WEIGHT - 1.0))
    red.note_refit({"ici_byte_weight": float(w),
                    "rows_used": weights.rows_used,
                    "source": weights.source})
    if switcher is not None:
        switcher.set_weights(weights)
    return weights


# -- module-level conveniences (the call-site API) ----------------------------


def observe(series: str, value: float, weight: float = 1.0,
            t_ms: Optional[float] = None) -> None:
    REDUCER.observe(series, value, weight=weight, t_ms=t_ms)


def poll(plan_row=None, now_ms: Optional[float] = None) -> int:
    return REDUCER.poll(plan_row=plan_row, now_ms=now_ms)


def evaluate(now_ms: Optional[float] = None) -> List[dict]:
    return REDUCER.evaluate(now_ms=now_ms)


def alerts(**kw) -> List[dict]:
    return REDUCER.alerts(**kw)


def describe(**kw) -> dict:
    return REDUCER.describe(**kw)


def health_view() -> dict:
    return REDUCER.health_view()


def dump_state() -> tuple:
    return REDUCER.dump_state()


def restore_state(state: tuple) -> None:
    REDUCER.restore_state(state)


def clear() -> None:
    REDUCER.clear()
