"""graftscope: always-on, low-overhead device-time attribution.

The serving stack had span trees, histograms, and a flight recorder
(utils.tracing, utils.metrics) — but nothing ever recorded *which
compiled program* a unit of wall time went to, so the cost model's
predictions (tools/graftcheck/costmodel.py) were never confronted with
measured device time. This module closes that loop with three pieces:

- **per-program dispatch rings**: every declared jit entry point's
  dispatch site is wrapped by ``instrument`` (declared per module in
  ``PROFILED_SCOPES`` beside ``JIT_ENTRY_POINTS``; the graftcheck
  ``unprofiled-entry-point`` rule verifies every entry point is either
  wrapped or baselined with a justification). Each call records one
  bounded-ring sample ``(t, program_key, seconds)`` — the key derived
  by the call site's ``key_fn`` from the ACTUAL call operands, in the
  same model ``tools/graftcheck/recompile.py`` certifies, so
  ``python -m tools.graftcheck scope`` can join measured rings against
  certified program populations 1:1;
- **occupancy time series**: bounded rings of ``(t, value)`` points for
  the live-state gauges (pool blocks in use, batch occupancy, queue
  depth), sampled at the schedulers' existing decision points — the
  trajectory behind the instantaneous /metrics gauges;
- **the /debug/profile view**: ``snapshot()`` serves both, bounded, at
  ``GET /debug/profile`` (serving/app.py).

Truth model (the same honesty contract utils.tracing documents): jax
dispatch is ASYNC, so by default a dispatch sample measures the
serving-thread wall clock around ENQUEUE — cheap enough to stay on for
every production dispatch, but NOT device time. ``set_sync(True)`` (or
``GRAFTSCOPE_SYNC=1``) makes every instrumented dispatch close its
window through ``jax.block_until_ready`` (``tracing.timed(sync=...)``):
device-true attribution at the price of serialized dispatch — what the
``graftcheck scope`` attribution run uses, never the serving default.

Overhead: one enabled-flag check, two ``perf_counter`` reads, one
histogram observation, and one deque append per dispatch. The pinned
bound (tests/test_graftscope.py): a quick-tier decode run with rings
enabled stays within ``OVERHEAD_FACTOR`` of rings-disabled wall time,
and every ring is bounded regardless of traffic volume. ``GRAFTSCOPE=0``
disables recording entirely (the wrapper short-circuits).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from . import graftsched, grafttime, tracing

# Lock-discipline contract (tools/graftcheck locks pass): the dispatch
# rings and the time-series points are written by scheduler/handler
# threads and read by /debug/profile handlers concurrently — both maps
# live under the state instance's ``_lock``.
GUARDED_STATE = {"_rings": "_lock", "_points": "_lock"}
LOCK_ORDER = ("_lock",)

# Timeline contract (tools/graftcheck timeline pass): every
# instrumented dispatch publishes begin/end onto the unified causal
# stream (utils/grafttime) with the certifier's program key, and every
# occupancy sample mirrors onto it — the same points /debug/profile
# serves, now join-able against spans/faults/switches on one clock.
TIMELINE_EVENTS = {
    "dispatch_begin": "ProfiledFn.__call__",
    "dispatch_end": "ProfiledFn.__call__",
    "occupancy": "sample",
}

# bounded-ring capacities: per-scope dispatch samples and per-series
# occupancy points kept (oldest dropped — a ring, not a log)
RING_CAPACITY = 256
SERIES_CAPACITY = 512
# distinct program keys tracked per scope: the compiled-program space is
# bounded by construction (the recompile budget proves it), so this cap
# only backstops a key-model bug; overflow aggregates under _OVERFLOW
KEY_CAPACITY = 512
_OVERFLOW = ("<key-overflow>",)

# The declared overhead bound tests/test_graftscope.py pins: a decode
# run with rings enabled must finish within this factor of the same run
# with rings disabled (generous — CPU wall clocks are noisy; the real
# per-dispatch cost is a few microseconds).
OVERHEAD_FACTOR = 2.0

_enabled = [os.environ.get("GRAFTSCOPE", "1") != "0"]
_sync = [os.environ.get("GRAFTSCOPE_SYNC", "0") not in ("", "0")]


def enabled() -> bool:
    return _enabled[0]


def set_enabled(value: bool) -> bool:
    """Toggle recording (returns the previous value). The overhead test
    uses this for its rings-disabled baseline; production leaves it on."""
    prev = _enabled[0]
    _enabled[0] = bool(value)
    return prev


def sync_enabled() -> bool:
    return _sync[0]


def set_sync(value: bool) -> bool:
    """Toggle device-true dispatch windows (block_until_ready before
    each sample closes — see the module docstring's truth model)."""
    prev = _sync[0]
    _sync[0] = bool(value)
    return prev


class ScopeState:
    """The process-wide attribution state: per-scope dispatch rings +
    per-series occupancy points, all bounded."""

    def __init__(self):
        self._lock = graftsched.lock("graftscope.ScopeState._lock")
        # scope -> {"samples": deque[(t, key, secs)],
        #           "programs": {key: [calls, secs]}}
        self._rings: Dict[str, dict] = {}
        # (name, labels-kv-tuple) -> deque[(t, value)]
        self._points: Dict[Tuple[str, tuple], deque] = {}
        self.t0 = time.perf_counter()

    # -- recording -----------------------------------------------------------

    def record(self, scope: str, key: tuple, seconds: float) -> None:
        now = time.perf_counter()
        with self._lock:
            ring = self._rings.get(scope)
            if ring is None:
                ring = self._rings[scope] = {
                    "samples": deque(maxlen=RING_CAPACITY), "programs": {}}
            programs = ring["programs"]
            if key not in programs and len(programs) >= KEY_CAPACITY:
                key = _OVERFLOW
            stat = programs.setdefault(key, [0, 0.0])
            stat[0] += 1
            stat[1] += seconds
            ring["samples"].append((now, key, seconds))

    def sample(self, name: str, value: float, **labels) -> None:
        now = time.perf_counter()
        skey = (name, tuple(sorted(labels.items())))
        with self._lock:
            pts = self._points.get(skey)
            if pts is None:
                pts = self._points[skey] = deque(maxlen=SERIES_CAPACITY)
            pts.append((now, float(value)))

    # -- reading -------------------------------------------------------------

    def program_keys(self, scope: str) -> Dict[tuple, Tuple[int, float]]:
        """``{program_key: (calls, seconds_total)}`` for one scope —
        what ``tools/graftcheck scope`` joins against the certifier."""
        with self._lock:
            ring = self._rings.get(scope)
            if ring is None:
                return {}
            return {k: (v[0], v[1]) for k, v in ring["programs"].items()}

    def scope_seconds(self, scope: str) -> float:
        with self._lock:
            ring = self._rings.get(scope)
            if ring is None:
                return 0.0
            return sum(v[1] for v in ring["programs"].values())

    def _series_totals_locked(self) -> Dict[str, dict]:
        # caller holds self._lock. Whole-ring reductions, independent
        # of any ?n= window: a step-function series (hop_breaker_open
        # samples only on HopPolicy TRANSITIONS) whose last point
        # predates a windowed view would otherwise vanish while the
        # breaker is still open — "last" is the series' CURRENT value
        # by construction
        out: Dict[str, dict] = {}
        for (name, labels), pts in sorted(self._points.items()):
            label = name + ("{%s}" % ",".join(
                f"{k}={v}" for k, v in labels) if labels else "")
            vals = [v for _, v in pts]
            out[label] = {
                "points": len(vals),
                "last": vals[-1],
                "max": max(vals),
                "min": min(vals),
            }
        return out

    def series_totals(self) -> Dict[str, dict]:
        """The window-independent per-series reductions alone — walks
        only the occupancy points, never the dispatch rings, so
        consumers that want current values (the graftwatch signal
        view, polled at /debug/plan) don't build the full per-scope
        key tables under the lock every instrumented dispatch's
        ``record`` contends on."""
        with self._lock:
            return self._series_totals_locked()

    def snapshot(self, n: int = 32) -> dict:
        """Bounded JSON view (the /debug/profile payload body): per-scope
        totals + the last ``n`` ring samples, per-series last ``n``
        points. Times are milliseconds relative to process attribution
        start; program keys are stringified."""
        n = max(int(n), 0)
        with self._lock:
            dispatch = {}
            for scope in sorted(self._rings):
                ring = self._rings[scope]
                programs = ring["programs"]
                # the per-key table is payload-bounded independently of
                # KEY_CAPACITY: hottest keys first, and a truncation is
                # MARKED (a silent cap would read as "all programs
                # shown" exactly when a key-model bug mints too many)
                top = sorted(programs.items(),
                             key=lambda kv: kv[1][1], reverse=True)
                entry = {
                    "calls": sum(v[0] for v in programs.values()),
                    "seconds_total": round(
                        sum(v[1] for v in programs.values()), 6),
                    "programs": len(programs),
                    "keys": {
                        repr(k): {"calls": v[0],
                                  "seconds_total": round(v[1], 6)}
                        for k, v in top[:64]},
                    "ring": [
                        {"t_ms": round((t - self.t0) * 1e3, 3),
                         "key": repr(k), "ms": round(s * 1e3, 4)}
                        for t, k, s in
                        (list(ring["samples"])[-n:] if n else [])],
                }
                if len(programs) > 64:
                    entry["keys_truncated"] = True
                dispatch[scope] = entry
            series = {}
            for (name, labels), pts in sorted(self._points.items()):
                label = name + ("{%s}" % ",".join(
                    f"{k}={v}" for k, v in labels) if labels else "")
                series[label] = [
                    [round((t - self.t0) * 1e3, 3), v]
                    for t, v in (list(pts)[-n:] if n else [])]
            series_totals = self._series_totals_locked()
        return {
            "enabled": enabled(),
            "sync": sync_enabled(),
            "ring_capacity": RING_CAPACITY,
            "series_capacity": SERIES_CAPACITY,
            # the honesty header (same contract as utils.tracing): what
            # these numbers are and are not
            "truth": ("dispatch samples measure serving-thread wall "
                      "clock around enqueue (async dispatch); sync mode "
                      "closes windows via block_until_ready = device "
                      "truth, used by graftcheck scope attribution runs"),
            "dispatch": dispatch,
            "series": series,
            "series_totals": series_totals,
        }

    # -- test isolation (tests/conftest.py) ----------------------------------

    def dump_state(self) -> tuple:
        with self._lock:
            rings = {
                scope: {"samples": list(ring["samples"]),
                        "programs": {k: list(v)
                                     for k, v in ring["programs"].items()}}
                for scope, ring in self._rings.items()}
            points = {k: list(v) for k, v in self._points.items()}
        return rings, points, self.t0

    def restore_state(self, state: tuple) -> None:
        rings, points, t0 = state
        with self._lock:
            self._rings = {
                scope: {"samples": deque(ring["samples"],
                                         maxlen=RING_CAPACITY),
                        "programs": {k: list(v)
                                     for k, v in ring["programs"].items()}}
                for scope, ring in rings.items()}
            self._points = {k: deque(v, maxlen=SERIES_CAPACITY)
                            for k, v in points.items()}
            self.t0 = t0

    def clear(self) -> None:
        with self._lock:
            self._rings = {}
            self._points = {}
            self.t0 = time.perf_counter()


# process-wide default state (what serving.app and the instrumented
# entry points use; tests snapshot/restore it via the conftest fixture)
STATE = ScopeState()


def _default_key(args, kwargs) -> tuple:
    """Shape-derived fallback program key for entry points without a
    hand-written ``key_fn``: array operand shapes + hashable statics —
    a superset-faithful stand-in for the jit cache key (same operand
    shapes/statics -> same key)."""
    parts = []
    for a in args:
        shp = getattr(a, "shape", None)
        if shp is not None:
            parts.append(tuple(shp))
    for k in sorted(kwargs):
        v = kwargs[k]
        parts.append((k, v if isinstance(v, (int, float, str, bool,
                                             type(None))) else repr(v)))
    return tuple(parts)


class ProfiledFn:
    """Callable wrapper timing every dispatch of one jitted entry point
    into the scope ring (plus the ``dispatch_seconds`` histogram via
    ``tracing.timed`` — whose ``sync=`` mode supplies device truth when
    armed). Transparent otherwise: attributes (``_cache_size``, etc.)
    forward to the wrapped jit object, so CompileWatch and the
    recompile-budget tests see the real cache."""

    __slots__ = ("_fn", "_scope", "_key_fn")

    def __init__(self, fn, scope: str,
                 key_fn: Optional[Callable] = None):
        self._fn = fn
        self._scope = scope
        self._key_fn = key_fn

    def __call__(self, *args, **kwargs):
        if not _enabled[0]:
            return self._fn(*args, **kwargs)
        try:
            key = (self._key_fn(*args, **kwargs)
                   if self._key_fn is not None
                   else _default_key(args, kwargs))
        except Exception:  # noqa: BLE001 — a key-model slip must never
            key = ("<unkeyed>",)  # cost the dispatch its result
        krepr = repr(key)
        grafttime.emit("dispatch_begin", scope=self._scope, key=krepr)
        with tracing.timed("dispatch_seconds", sync=_sync[0],
                           scope=self._scope) as h:
            out = h.sync(self._fn(*args, **kwargs))
        STATE.record(self._scope, key, h.seconds)
        grafttime.emit("dispatch_end", scope=self._scope, key=krepr,
                       dur_ms=round(h.seconds * 1e3, 4))
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def instrument(fn, scope: str,
               key_fn: Optional[Callable] = None) -> ProfiledFn:
    """Wrap a jitted callable for dispatch-ring attribution. THE form
    the graftcheck ``unprofiled-entry-point`` rule recognizes at jit
    sites: ``self._x = graftscope.instrument(jax.jit(...), "mod._x",
    key_fn=...)``. ``key_fn(*call args)`` must return the program key in
    the model ``tools/graftcheck/recompile.py`` certifies for this entry
    point (omit it for entry points outside the certifier's model — the
    shape-derived default key still distinguishes programs)."""
    return ProfiledFn(fn, scope, key_fn)


# -- module-level conveniences (the call-site API) ---------------------------


def record(scope: str, key: tuple, seconds: float) -> None:
    if _enabled[0]:
        STATE.record(scope, key, seconds)


def sample(name: str, value: float, **labels) -> None:
    """Append one occupancy point to the bounded time-series ring.
    ``name`` must be a METRIC_CATALOG gauge (the metric-catalog rule
    scans these call sites too) — the series is the trajectory behind
    the same-named /metrics gauge. Each point also mirrors onto the
    unified timeline (grafttime kind ``occupancy``), so live-state
    trajectories sit on the same clock as spans and dispatches."""
    if _enabled[0]:
        STATE.sample(name, value, **labels)
        grafttime.emit("occupancy", name=name, value=float(value),
                       **labels)


def now_ms() -> float:
    """The current instant on the snapshot timeline (milliseconds since
    attribution start — the same clock ``snapshot``'s ``t_ms`` fields
    use). Lets a caller window series points to one measurement run
    (e.g. loadgen's per-run occupancy summary) without touching the
    shared rings."""
    return (time.perf_counter() - STATE.t0) * 1e3


def program_keys(scope: str) -> Dict[tuple, Tuple[int, float]]:
    return STATE.program_keys(scope)


def scope_seconds(scope: str) -> float:
    return STATE.scope_seconds(scope)


def series_totals() -> Dict[str, dict]:
    return STATE.series_totals()


def snapshot(n: int = 32) -> dict:
    return STATE.snapshot(n=n)


def dump_state() -> tuple:
    return STATE.dump_state()


def restore_state(state: tuple) -> None:
    STATE.restore_state(state)


def clear() -> None:
    STATE.clear()
