"""graftfault: seeded fault injection + degraded-mode serving primitives.

The dynamic half of the graftcheck faults pass (``tools/graftcheck/
faults.py`` is the static half — the same static+dynamic split as
graftsan and graftlock). The serving topology is coordinator-plus-shards
(and, per ROADMAP item 2, a disaggregated fleet next), where Helix-style
placement economics make preemption, eviction, and replica failure
steady-state events — so the failure paths need the same deterministic,
replayable test harness the race and memory hazards already have.

Three things live here:

**Seeded fault injection** (``GRAFTFAULT=1`` or an installed
:class:`FaultPlan`): production fault boundaries call
:func:`inject(site, *kinds)` — a no-op returning ``None`` when no plan
is armed (zero cost on the serving path). With a plan armed, the k-th
call at a site deterministically maps to an outcome via
``hash(seed, site, k)``: the SAME seed replays the SAME per-site
outcome sequence regardless of wall clock (thread interleaving can
reorder which request sees outcome k, but the site's outcome sequence
is pinned — the same determinism contract as GRAFTSCHED schedules).
Injected kinds mirror the real failure classes: hop connection
reset/timeout/slow-response, shard 5xx, pool-exhaustion spikes, and
mid-decode engine exceptions (transient and permanent). Every firing is
logged with ``file:line (func)`` provenance (``FaultPlan.injections``).

**Deadline budgets** (:class:`Deadline`): one per-request monotonic
deadline, derived from the ``X-Deadline-Ms`` request header, that every
blocking hop downstream derives its own timeout from — the static
``deadline-drop`` rule exists to keep that derivation honest.

**HopPolicy** (typed retry + circuit breaker): the cross-process hop
discipline replacing ad-hoc ``timeout=30`` + one-retry loops. Capped
exponential backoff with seeded jitter, a per-request retry budget, and
a per-shard circuit breaker (CLOSED -> OPEN after ``breaker_threshold``
consecutive failures -> HALF-OPEN probe after ``breaker_cooldown_s`` ->
CLOSED on probe success). An open breaker raises
:class:`CircuitOpenError` (-> a typed 503 + Retry-After from serving)
instead of queueing more work behind a dead dependency.

Typed unavailability (:class:`Unavailable` and subclasses) is the
degraded-mode contract: serving maps it to 503 + ``Retry-After`` with
the X-Request-ID echoed, never an opaque 500.

Env knobs: ``GRAFTFAULT`` ("" / ``0`` off, ``1`` armed),
``GRAFTFAULT_SEED`` (int, default 0), ``GRAFTFAULT_RATE`` (float,
default 0.1), ``GRAFTFAULT_SITES`` / ``GRAFTFAULT_KINDS``
(comma-separated filters; empty = all). Tests prefer an explicit
``install(FaultPlan(...))`` / ``use(plan)`` so the plan's injection log
is directly assertable.

Like graftsched, this module is measurement apparatus: it is excluded
from the static faults pass's own scan.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import graftsched

__all__ = [
    "CircuitOpenError", "Deadline", "DeadlineExceeded", "FaultBudgetError",
    "FaultPlan", "HopPolicy", "Injection", "PermanentFault",
    "TransientFault", "Unavailable", "enabled", "inject", "install",
    "plan", "reset", "seed", "use",
]

# Lock-discipline contract (tools/graftcheck locks pass): the plan's
# per-site counters/log and the policy's breaker table are touched from
# arbitrary serving/scheduler threads; each lives under its owning
# instance's ``_lock``. Backoff sleeps and hop attempts run OUTSIDE any
# hold (the blocking-under-lock rule pins that).
GUARDED_STATE = {"_inj_counts": "_lock", "_inj_log": "_lock",
                 "_breakers": "_lock",
                 "_PLAN": "_PLAN_LOCK", "_ENV_PLAN": "_PLAN_LOCK"}
LOCK_ORDER = ("_PLAN_LOCK", "_lock")

# Timeline contract (tools/graftcheck timeline pass): every fired
# injection and every breaker state TRANSITION lands on the unified
# causal stream (utils/grafttime) — a re-planning or degraded-mode
# decision is only auditable if the fault that provoked it sits on the
# same clock as the recovery it triggered.
TIMELINE_EVENTS = {
    "fault_inject": "FaultPlan.fire",
    "breaker": "_sample_breaker (HopPolicy transitions)",
}


def enabled() -> bool:
    return os.environ.get("GRAFTFAULT", "") not in ("", "0")


def seed() -> int:
    try:
        return int(os.environ.get("GRAFTFAULT_SEED", "0"))
    except ValueError:
        return 0


def _env_rate() -> float:
    try:
        return float(os.environ.get("GRAFTFAULT_RATE", "0.1"))
    except ValueError:
        return 0.1


def _env_set(name: str) -> Optional[frozenset]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    return frozenset(p.strip() for p in raw.split(",") if p.strip())


def _call_site() -> str:
    """``file.py:line (func)`` of the nearest frame outside this module
    — the provenance every injection record carries (graftsched's
    helper, told to skip THIS module's frames)."""
    return graftsched._call_site(skip_file=__file__)


# -- typed faults -------------------------------------------------------------


class InjectedFault(RuntimeError):
    """Base of every deterministically injected failure."""

    def __init__(self, site: str, kind: str, message: str):
        super().__init__(message)
        self.site = site
        self.kind = kind


class TransientFault(InjectedFault):
    """A failure the degraded path must absorb: the iter scheduler parks
    the affected rows via the recompute-resume machinery and replays
    them byte-identically."""


class Unavailable(RuntimeError):
    """Typed degraded-mode unavailability: serving answers 503 with
    ``Retry-After = retry_after`` (rounded up, >= 1s) and the request's
    X-Request-ID echoed — the caller knows to back off, monitoring sees
    a typed error, and nothing surfaces as an opaque 500."""

    code = "unavailable"

    def __init__(self, message: str, retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = max(float(retry_after), 0.0)


class PermanentFault(InjectedFault, Unavailable):
    """An injected engine failure the degraded path must NOT retry: the
    affected rows fail with their partial trace flight-recorded and the
    caller gets the typed 503."""

    code = "engine_fault"

    def __init__(self, site: str, kind: str, message: str,
                 retry_after: float = 1.0):
        InjectedFault.__init__(self, site, kind, message)
        self.retry_after = max(float(retry_after), 0.0)


class CircuitOpenError(Unavailable):
    """The per-shard breaker is OPEN: the hop was not even attempted."""

    code = "circuit_open"


class DeadlineExceeded(Unavailable):
    """The request's deadline budget ran out (X-Deadline-Ms, or a
    caller-supplied ``deadline=``); in-flight rows are cancelled at the
    next segment boundary with their blocks freed."""

    code = "deadline_exceeded"


class FaultBudgetError(Unavailable):
    """A row exhausted its transient-fault park budget — repeated
    recovery attempts failed; the caller should retry elsewhere/later."""

    code = "fault_budget_exhausted"


# -- deadline budget ----------------------------------------------------------


class Deadline:
    """One monotonic per-request deadline, threaded end-to-end: HTTP
    wait, queue wait, shard-hop timeouts, and segment-boundary
    cancellation all derive their budgets from ``remaining()``."""

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = float(at)

    @classmethod
    def from_ms(cls, ms: float) -> "Deadline":
        return cls(time.monotonic() + float(ms) / 1e3)

    def remaining(self) -> float:
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def timeout(self, cap: float) -> float:
        """A per-attempt timeout derived from the remaining budget,
        never exceeding ``cap`` and never non-positive (a zero timeout
        would mean "no timeout" to several libraries)."""
        return max(min(float(cap), self.remaining()), 1e-3)

    def raise_if_expired(self, what: str = "request") -> None:
        if self.expired():
            raise DeadlineExceeded(
                f"{what}: deadline budget exhausted "
                f"({-self.remaining() * 1e3:.0f}ms past)")


# -- the seeded plan ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Injection:
    """One fired fault, with provenance — what the must-find fixtures
    pin (site, kind, per-site sequence number, ``file:line (func)``)."""

    site: str
    kind: str
    seq: int
    where: str


class FaultPlan:
    """Deterministic, replay-identical fault schedule.

    The k-th ``fire`` at a site hashes ``(seed, site, k)`` into its own
    RNG: whether it fires and which kind it picks is a pure function of
    those three values, so a pinned seed replays the same per-site
    outcome sequence — :meth:`preview` exposes that sequence without
    consuming it, which is how tests pin replay identity.

    ``sites`` / ``kinds`` filter where faults may land (None = all);
    ``max_injections`` bounds the total fired (surgical fixtures:
    "exactly one transient decode fault")."""

    def __init__(self, seed: int = 0, rate: float = 0.1,
                 sites: Optional[Sequence[str]] = None,
                 kinds: Optional[Sequence[str]] = None,
                 max_injections: Optional[int] = None):
        self.seed = int(seed)
        self.rate = float(rate)
        self.sites = None if sites is None else frozenset(sites)
        self.kinds = None if kinds is None else frozenset(kinds)
        self.max_injections = max_injections
        self._lock = graftsched.lock("graftfault.FaultPlan._lock")
        self._inj_counts: Dict[str, int] = {}
        self._inj_log: List[Injection] = []

    def _decide(self, site: str, n: int,
                kinds: Tuple[str, ...]) -> Optional[str]:
        """The pure (seed, site, n) -> outcome function."""
        if self.sites is not None and site not in self.sites:
            return None
        allowed = [k for k in kinds
                   if self.kinds is None or k in self.kinds]
        if not allowed:
            return None
        rng = random.Random(f"{self.seed}/{site}/{n}")
        if rng.random() >= self.rate:
            return None
        return allowed[rng.randrange(len(allowed))]

    def preview(self, site: str, kinds: Sequence[str],
                n: int) -> List[Optional[str]]:
        """The first ``n`` outcomes the plan would produce at ``site``
        — pure, counter-free: two plans with the same seed preview
        identically (the replay pin)."""
        return [self._decide(site, i, tuple(kinds)) for i in range(n)]

    def fire(self, site: str, kinds: Sequence[str]) -> Optional[str]:
        with self._lock:
            n = self._inj_counts.get(site, 0)
            self._inj_counts[site] = n + 1
            budget_left = (self.max_injections is None
                           or len(self._inj_log) < self.max_injections)
        if not budget_left:
            return None
        kind = self._decide(site, n, tuple(kinds))
        if kind is None:
            return None
        inj = Injection(site, kind, n, _call_site())
        with self._lock:
            if (self.max_injections is not None
                    and len(self._inj_log) >= self.max_injections):
                return None
            self._inj_log.append(inj)
        # the fired fault on the causal timeline (rid rides the ambient
        # correlation: the scheduler's live-row set, or the request
        # trace); lazy import — measurement apparatus bootstraps first
        from . import grafttime
        grafttime.emit("fault_inject", site=site, fault=kind, seq=n,
                       where=inj.where)
        return kind

    @property
    def injections(self) -> List[Injection]:
        with self._lock:
            return list(self._inj_log)


# -- ambient plan plumbing ----------------------------------------------------

_PLAN_LOCK = threading.Lock()   # module bootstrap only; never contended
_PLAN: Optional[FaultPlan] = None
_ENV_PLAN: Optional[FaultPlan] = None


def install(p: Optional[FaultPlan]) -> None:
    """Arm (or, with None, disarm) an explicit plan; it takes precedence
    over the env-built one."""
    global _PLAN
    with _PLAN_LOCK:
        _PLAN = p


@contextlib.contextmanager
def use(p: FaultPlan):
    """Scoped :func:`install` for tests."""
    install(p)
    try:
        yield p
    finally:
        install(None)


def reset() -> None:
    """Drop both the installed and the cached env-built plan (tests
    re-arm the env and want a fresh seed/rate read)."""
    global _PLAN, _ENV_PLAN
    with _PLAN_LOCK:
        _PLAN = None
        _ENV_PLAN = None


def plan() -> Optional[FaultPlan]:
    """The active plan: the installed one, else one built (once) from
    the GRAFTFAULT env contract, else None. The unarmed fast path is
    lock-free (one global ref read + one env lookup) — ``inject`` rides
    every decode segment and admission check, so the common
    production case must not serialize workers on a global lock."""
    p = _PLAN
    if p is not None:
        return p
    if not enabled():
        return None
    global _ENV_PLAN
    with _PLAN_LOCK:
        if _PLAN is not None:
            return _PLAN
        if _ENV_PLAN is None:
            _ENV_PLAN = FaultPlan(seed=seed(), rate=_env_rate(),
                                  sites=_env_set("GRAFTFAULT_SITES"),
                                  kinds=_env_set("GRAFTFAULT_KINDS"))
        return _ENV_PLAN


def inject(site: str, *kinds: str) -> Optional[str]:
    """The production hook: returns the injected kind, or None (always
    None with no plan armed — the only cost is one attribute read)."""
    p = plan()
    if p is None:
        return None
    return p.fire(site, kinds)


# -- the hop policy -----------------------------------------------------------


def _sample_breaker(target: str, value: float, registry=None) -> None:
    """One ``hop_breaker_open`` point per breaker state TRANSITION —
    1.0 at open, 0.0 when a probe closes it — labeled per TARGET: a
    HopPolicy keys one breaker per downstream (the coordinator's stage
    shards; the fleet router's N replicas, one breaker each), and an
    unlabeled gauge would collapse the fleet's breakers into one
    indistinguishable series. Emitted BOTH as a registry gauge (the
    scrapeable /metrics form — registered in METRIC_CATALOG, so the
    metric-catalog rule covers the labeled emission; the policy owner's
    injected registry when it has one, else the process default, so an
    app serving its own /metrics sees its own breakers) and as a
    graftscope occupancy point (the /debug/profile timeline a
    graftload run reduces). Lazy imports: this module must stay
    importable mid-bootstrap without the measurement apparatus."""
    from . import graftscope, grafttime
    from .metrics import REGISTRY
    (REGISTRY if registry is None else registry).gauge(
        "hop_breaker_open", value, target=target)
    graftscope.sample("hop_breaker_open", value, target=target)
    # the breaker TRANSITION as a typed timeline event (beyond the
    # occupancy point the sample above mirrors): state + target on the
    # same clock as the hop spans and fault injections around it
    grafttime.emit("breaker", state="open" if value else "closed",
                   target=target)


@dataclasses.dataclass
class _Breaker:
    """Per-shard breaker record (all fields under HopPolicy._lock)."""

    streak: int = 0            # consecutive failures
    opened_at: Optional[float] = None
    probing: bool = False      # HALF-OPEN probe in flight


class HopPolicy:
    """Typed retry/backoff/circuit-breaker discipline for one class of
    cross-process hops (e.g. coordinator -> stage shards).

    ``call(fn, shard=..., deadline=...)`` drives ``fn(timeout_s)``
    through up to ``attempts`` tries with capped exponential backoff and
    seeded jitter between them; every attempt's ``timeout_s`` is derived
    from the remaining deadline budget (capped at ``timeout_s``).
    Exceptions listed in ``fatal`` propagate immediately (no retry — a
    misroute does not get better with repetition). ``on_retry(shard,
    reason)`` fires before each re-attempt (serving counts it into
    ``shard_hop_retries_total{stage,reason}``).

    The per-shard breaker opens after ``breaker_threshold`` CONSECUTIVE
    failures: calls fail fast with :class:`CircuitOpenError` (Retry-After
    = remaining cooldown) instead of stacking timeouts behind a dead
    shard. After ``breaker_cooldown_s`` one probe call is let through
    (HALF-OPEN); success closes the breaker, failure re-opens it.
    """

    def __init__(self, attempts: int = 3, timeout_s: float = 30.0,
                 base_backoff_s: float = 0.05, max_backoff_s: float = 1.0,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 5.0,
                 jitter_seed: int = 0, fatal: Tuple[type, ...] = (),
                 on_retry=None, sleep=time.sleep, registry=None):
        if attempts < 1:
            raise ValueError("attempts must be >= 1")
        # breaker gauges land here (None = the process REGISTRY); an
        # app built around an injected MetricsRegistry passes its own
        # so its /metrics shows its own breakers
        self.registry = registry
        self.attempts = attempts
        self.timeout_s = float(timeout_s)
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.fatal = tuple(fatal)
        self.on_retry = on_retry
        self._sleep = sleep
        self._lock = graftsched.lock("graftfault.HopPolicy._lock")
        self._rng = random.Random(jitter_seed)
        self._breakers: Dict[str, _Breaker] = {}

    # -- breaker transitions (each a single lock hold) --

    def _gate(self, shard: str) -> None:
        """Admission through the breaker; raises CircuitOpenError or
        marks the HALF-OPEN probe, atomically."""
        now = time.monotonic()
        with self._lock:
            b = self._breakers.setdefault(shard, _Breaker())
            if b.opened_at is None:
                return
            waited = now - b.opened_at
            if waited < self.breaker_cooldown_s:
                raise CircuitOpenError(
                    f"shard {shard!r} circuit open "
                    f"({b.streak} consecutive failures)",
                    retry_after=self.breaker_cooldown_s - waited)
            if b.probing:
                raise CircuitOpenError(
                    f"shard {shard!r} circuit half-open: a probe is "
                    "already in flight",
                    retry_after=self.breaker_cooldown_s)
            b.probing = True   # this call IS the probe

    def _note_failure(self, shard: str) -> bool:
        """Record a failed attempt; returns True when the breaker is
        now open (the caller stops retrying)."""
        now = time.monotonic()
        with self._lock:
            b = self._breakers.setdefault(shard, _Breaker())
            b.streak += 1
            opened = b.probing or b.streak >= self.breaker_threshold
            if opened:
                b.opened_at = now       # open (or re-open after a probe)
                b.probing = False
                # breaker state rides the graftscope occupancy series:
                # a graftload run sees breaker flaps on the same
                # timeline as queue depth and pool blocks
                # (/debug/profile "series"). Sampled UNDER the hold so
                # a concurrent open/close pair can never land its
                # points in inverted order (a cheap ring append, not a
                # blocking call — the blocking-under-lock class).
                _sample_breaker(shard, 1.0, self.registry)
        return opened

    def _note_success(self, shard: str) -> None:
        with self._lock:
            was_open = (shard in self._breakers
                        and self._breakers[shard].opened_at is not None)
            self._breakers[shard] = _Breaker()   # fully closed
            if was_open:
                _sample_breaker(shard, 0.0, self.registry)  # probe closed it

    def _probe_release(self, shard: str) -> None:
        """Clear a HALF-OPEN probe claim that ended without a verdict
        (deadline raised before the attempt ran, or a non-Exception
        unwound it) — otherwise the stuck flag would wedge the breaker
        open forever. Idempotent: a probe that already resolved through
        note_failure/note_success left ``probing`` False."""
        with self._lock:
            b = self._breakers.get(shard)
            if b is not None:
                b.probing = False

    def breaker_state(self, shard: str) -> str:
        now = time.monotonic()
        with self._lock:
            b = self._breakers.get(shard)
            if b is None or b.opened_at is None:
                return "closed"
            if b.probing:
                return "half-open"
            if now - b.opened_at >= self.breaker_cooldown_s:
                return "half-open"
            return "open"

    def _backoff(self, attempt: int) -> float:
        """Capped exponential with seeded jitter in [0.5x, 1.5x)."""
        base = min(self.base_backoff_s * (2 ** (attempt - 1)),
                   self.max_backoff_s)
        with self._lock:
            j = 0.5 + self._rng.random()
        return base * j

    def call(self, fn, *, shard: str,
             deadline: Optional[Deadline] = None):
        """Drive ``fn(timeout_s)`` through the policy. Raises the last
        attempt's exception when the retry budget is exhausted,
        :class:`CircuitOpenError` when the breaker is (or goes) open,
        :class:`DeadlineExceeded` when the budget ran out."""
        self._gate(shard)
        try:
            return self._call_gated(fn, shard=shard, deadline=deadline)
        except BaseException:
            # any exit that reached neither note_failure nor
            # note_success (pre-attempt deadline, KeyboardInterrupt
            # mid-fn) must not leak a HALF-OPEN probe claim
            self._probe_release(shard)
            raise

    def _call_gated(self, fn, *, shard: str,
                    deadline: Optional[Deadline] = None):
        last: Optional[BaseException] = None
        for attempt in range(self.attempts):
            if attempt:
                delay = self._backoff(attempt)
                if deadline is not None \
                        and deadline.remaining() <= delay:
                    break   # no budget left to wait out the backoff
                self._sleep(delay)
            if deadline is not None:
                deadline.raise_if_expired(f"hop to shard {shard!r}")
            t = (self.timeout_s if deadline is None
                 else deadline.timeout(self.timeout_s))
            try:
                out = fn(t)
            except self.fatal:
                # a fatal class still counts against the shard's streak
                # (a misrouted/erroring shard is an unhealthy shard)
                self._note_failure(shard)
                raise
            except Exception as e:  # noqa: BLE001 — retried per policy
                last = e
                opened = self._note_failure(shard)
                if opened:
                    raise CircuitOpenError(
                        f"shard {shard!r} circuit opened after repeated "
                        f"failures (last: {type(e).__name__}: {e})",
                        retry_after=self.breaker_cooldown_s) from e
                if (self.on_retry is not None
                        and attempt + 1 < self.attempts):
                    self.on_retry(shard, _failure_reason(e))
                continue
            self._note_success(shard)
            return out
        if deadline is not None and deadline.expired():
            raise DeadlineExceeded(
                f"hop to shard {shard!r}: deadline budget exhausted "
                f"after {self.attempts} attempt(s)") from last
        assert last is not None
        raise last


def _failure_reason(e: BaseException) -> str:
    """Stable low-cardinality reason label for retry metrics."""
    name = type(e).__name__.lower()
    if "timeout" in name:
        return "timeout"
    if "connection" in name:
        return "connection"
    if "http" in name:
        return "http_error"
    return "error"
