"""graftshard: the live placement auditor (dynamic half).

``tools/graftcheck/placement.py`` is the static half — the same
static+dynamic split as graftsan/graftsched/graftmem/graftnum. The
static pass verifies what the TRACED programs establish; this module
verifies what the LIVE buffers actually are: every device holding the
graftmem ledger registers (``graftmem.track`` / ``graftmem.update`` —
the one moment the value itself is in hand) is checked against the
owning module's declared ``PLACEMENT_CONTRACT``, so graftmem's
per-device byte attribution is finally held to a declaration instead
of just reported.

Armed by ``GRAFTSHARD=1`` (off by default: serving pays zero cost —
the hook is one env check per ledger registration). When armed:

- at ``track``/``update`` time the registered value's ``.sharding``
  (every leaf's PartitionSpec axis names, plus the addressable-shards
  device set) is checked against the owner module's
  ``PLACEMENT_CONTRACT["holding:<name>"]`` declaration;
- a declared ``"replicated"`` holding whose live buffer names ANY mesh
  axis — or a declared-axis holding naming any OTHER axis — raises
  :class:`GraftshardError` with holding/component/declaration-site
  provenance. The check is spec-level and device-count-independent: a
  single-device buffer (no named placement) satisfies every
  declaration; a buffer PLACED over an axis must be placed over the
  declared one.
- :func:`audit` re-walks every still-live tracked value (weak refs —
  the ledger's own lifecycle) and returns the violations; ``/healthz``
  surfaces :func:`status`.

``MESH_AXES`` mirrors ``tools/graftcheck/placement.MESH_AXES`` — the
tests pin the two stay equal (the graftnum.REGIMES pattern), so the
dynamic auditor and the static pass can never disagree about the
vocabulary.
"""

from __future__ import annotations

import os
import sys
import threading
import weakref
from typing import Any, Dict, List, Optional, Set, Tuple

# THE mesh-axis vocabulary (pinned equal to tools/graftcheck/
# placement.MESH_AXES by tests/test_graftshard.py)
MESH_AXES = ("pp", "tp", "ep", "kvp", "dp", "sp")

# locks-pass contract: graftmem's track/update/release fire from both
# serving threads and the iterbatch worker, so the auditor's registry
# and counters ride one instance lock (the MemoryLedger pattern)
GUARDED_STATE = {"_registry": "_lock", "_live": "_lock",
                 "_stats": "_lock"}

REPLICATED = "replicated"


class GraftshardError(AssertionError):
    """A live buffer's placement disagrees with its module's declared
    PLACEMENT_CONTRACT. AssertionError subclass for the same reason
    GraftsanError is: this is an invariant violation, not an
    environmental failure — tests must not catch it by accident."""

    def __init__(self, message: str, owner: str = "", holding: str = "",
                 component: str = "", expected: str = "",
                 found: Tuple[str, ...] = (), where: str = ""):
        super().__init__(message)
        self.owner = owner
        self.holding = holding
        self.component = component
        self.expected = expected
        self.found = tuple(found)
        self.where = where


def enabled() -> bool:
    return os.environ.get("GRAFTSHARD", "0") == "1"


class _Auditor:
    """The registry + counters behind the module-level API: handle ->
    declaration row plus a weak ref to the live value (refs die with
    the buffers, exactly like graftmem's finalizers)."""

    def __init__(self):
        self._lock = threading.Lock()
        # handle -> (module_name, owner_type, holding, component,
        #            expected)
        self._registry: Dict[int, Tuple[str, str, str, str, str]] = {}
        self._live: Dict[int, "weakref.ref"] = {}
        self._stats = {"checks": 0, "violations": 0}

    def register(self, handle: int, row: Tuple[str, str, str, str, str],
                 value: Any) -> None:
        with self._lock:
            self._registry[handle] = row
            try:
                self._live[handle] = weakref.ref(value)
            except TypeError:
                pass  # un-weakref-able values audit at track/update only

    def row(self, handle: int) -> Optional[Tuple[str, str, str, str, str]]:
        with self._lock:
            return self._registry.get(handle)

    def rebind(self, handle: int, value: Any) -> None:
        with self._lock:
            if handle not in self._registry:
                return
            try:
                self._live[handle] = weakref.ref(value)
            except TypeError:
                pass

    def drop(self, handle: int) -> None:
        with self._lock:
            self._registry.pop(handle, None)
            self._live.pop(handle, None)

    def live_rows(self):
        with self._lock:
            return [(h, self._registry[h], self._live[h])
                    for h in sorted(self._registry) if h in self._live]

    def count(self, checks: int = 0, violations: int = 0) -> None:
        with self._lock:
            self._stats["checks"] += checks
            self._stats["violations"] += violations

    def stats(self) -> dict:
        with self._lock:
            return {"checks": self._stats["checks"],
                    "violations": self._stats["violations"],
                    "tracked": len(self._registry)}

    def clear(self) -> None:
        with self._lock:
            self._registry.clear()
            self._live.clear()
            self._stats = {"checks": 0, "violations": 0}


STATE = _Auditor()


def _contract_of(module_name: str) -> Optional[dict]:
    mod = sys.modules.get(module_name)
    contract = getattr(mod, "PLACEMENT_CONTRACT", None)
    return contract if isinstance(contract, dict) else None


def _decl_site(module_name: str) -> str:
    """``file:line`` of the owning module's PLACEMENT_CONTRACT — the
    provenance every violation points back at."""
    mod = sys.modules.get(module_name)
    path = getattr(mod, "__file__", None)
    if not path:
        return module_name
    try:
        with open(path, encoding="utf-8") as f:
            for i, text in enumerate(f, 1):
                if text.startswith("PLACEMENT_CONTRACT"):
                    return f"{path}:{i}"
    except OSError:
        pass
    return path


def _leaf_axes(value: Any) -> Tuple[Set[str], int]:
    """(axis names any leaf's live PartitionSpec mentions, leaves
    inspected). Host arrays / single-device placements carry no spec
    and contribute nothing — the check is about NAMED placement."""
    import jax
    axes: Set[str] = set()
    seen = 0
    for leaf in jax.tree_util.tree_leaves(value):
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            continue
        seen += 1
        spec = getattr(sharding, "spec", None)
        if spec is None:
            continue
        for entry in spec:
            for a in (entry if isinstance(entry, tuple) else (entry,)):
                if isinstance(a, str):
                    axes.add(a)
        # the device set backs the spec claim: a spec naming axes while
        # the buffer sits on one device is still a single-device buffer
        shards = getattr(leaf, "addressable_shards", None)
        if shards is not None and len({s.device for s in shards}) <= 1 \
                and not axes:
            continue
    return axes, seen


def _problem(expected: str, axes: Set[str]) -> Optional[str]:
    if expected == REPLICATED:
        if axes:
            return (f"declared \"replicated\" but the live buffer is "
                    f"placed over mesh axes {sorted(axes)}")
        return None
    stray = axes - {expected}
    if stray:
        return (f"declared placement over {expected!r} but the live "
                f"buffer also names {sorted(stray)}")
    return None


def _check(module_name: str, owner_type: str, holding: str,
           component: str, expected: str, value: Any) -> None:
    axes, _seen = _leaf_axes(value)
    STATE.count(checks=1)
    problem = _problem(expected, axes)
    if problem is None:
        return
    STATE.count(violations=1)
    where = _decl_site(module_name)
    raise GraftshardError(
        f"graftshard: {owner_type}.{holding} (component {component!r}) "
        f"{problem} — contract at {where}",
        owner=owner_type, holding=holding, component=component,
        expected=expected, found=tuple(sorted(axes)), where=where)


def observe_track(owner: Any, holding: str, component: str, value: Any,
                  handle: int) -> None:
    """graftmem.track's hook: register + check one new holding. A
    module with no PLACEMENT_CONTRACT, or a contract not declaring
    this holding, audits nothing (declaring is the static pass's
    discipline; auditing the declared is this module's)."""
    if not enabled():
        return
    module_name = type(owner).__module__
    contract = _contract_of(module_name)
    if contract is None:
        return
    expected = contract.get(f"holding:{holding}")
    if not isinstance(expected, str):
        return
    row = (module_name, type(owner).__name__, holding, component,
           expected)
    STATE.register(handle, row, value)
    _check(*row, value)


def observe_update(handle: int, value: Any) -> None:
    """graftmem.update's hook: re-check a re-bound holding (the donated
    movers re-bind pool planes every dispatch — placement must
    survive the rebind)."""
    if not enabled():
        return
    row = STATE.row(handle)
    if row is None:
        return
    STATE.rebind(handle, value)
    _check(*row, value)


def observe_release(handle: int) -> None:
    STATE.drop(handle)


def audit() -> List[dict]:
    """Re-walk every still-live tracked holding against its declared
    contract; returns one row per VIOLATION (empty = the whole ledger
    is where it was declared to be). Never raises — /healthz and tests
    read the rows; the raising path is the track/update-time check."""
    out: List[dict] = []
    for _handle, row, ref in STATE.live_rows():
        module_name, owner_type, holding, component, expected = row
        value = ref()
        if value is None:
            continue
        axes, _seen = _leaf_axes(value)
        STATE.count(checks=1)
        problem = _problem(expected, axes)
        if problem is None:
            continue
        STATE.count(violations=1)
        out.append({
            "owner": owner_type, "holding": holding,
            "component": component, "expected": expected,
            "found": sorted(axes), "problem": problem,
            "where": _decl_site(module_name),
        })
    return out


def status() -> dict:
    """The /healthz view: armed or not, cumulative check/violation
    counters, and how many live holdings are under audit."""
    return {"enabled": enabled(), **STATE.stats()}


def clear() -> None:
    """Test hook: drop the registry and counters."""
    STATE.clear()
