"""graftmem: declared HBM ledger — live byte attribution + drift watch.

The spine could attribute device *time* (utils.graftscope) and causal
*order* (utils.grafttime) but not device *memory*: the cost model's
``hbm_bytes_per_device`` prediction (tools/graftcheck/costmodel.py) was
checked once in a golden test and never reconciled against the running
process. This module closes the byte gap the same way graftscope closed
the time gap — a declared contract, a live ledger, and a drift watch:

- **the ledger**: every long-lived device allocation registers with
  component provenance via ``track(owner, holding, component, value)``
  (model params, pool code/scale planes, contiguous caches, spec-decode
  buffers, prefix-store holdings — the :data:`MEMORY_COMPONENTS`
  vocabulary). Bytes are measured from the ACTUAL jax buffers
  (``leaf.nbytes`` over the registered pytree), never re-derived from
  shape arithmetic, so the ledger is the measured side of every
  measured-vs-modeled comparison. ``update`` re-measures a rebound
  holding; ``release`` retires it; a ``weakref.finalize`` on the owner
  retires anything a GC'd owner left behind.
- **the declared contract**: each runtime/ module lists
  ``MEMORY_LEDGER = {holding: component}`` beside JIT_ENTRY_POINTS;
  ``tools/graftcheck/memory.py`` statically verifies every persistent
  device-array attribute is declared, every declaration is live, and
  container accumulation of device arrays has a declared bound.
- **the drift watch**: ``reconcile(plan_row)`` confronts the cost
  model's ``param_bytes_per_device`` / pool-footprint predictions with
  the ledger's live bytes per component and reports the ratio —
  graftscope's measured-vs-modeled pattern, applied to bytes. bench.py
  journals it (``hbm_attribution``), bench_diff gates drift
  lower-better.

Every mutation samples the per-component total into graftscope's
occupancy rings (gauge ``hbm_bytes{component}``), publishes the same
gauge to /metrics, and lands a ``mem_alloc``/``mem_free`` byte-delta
event on the grafttime bus — so residency trajectories sit on the same
clock as the admissions, evictions, and plan switches that moved them.
``GET /debug/memory`` (serving/app.py) serves ``snapshot()``.

Conservation (the blocks_in_use+blocks_free==blocks_total discipline):
``snapshot()["conserved"]`` cross-checks the per-entry table against
the independently maintained running component/grand totals — /healthz
turns a disagreement into a 500, because a ledger that cannot account
for its own bytes must not report capacity.

``GRAFTMEM=0`` disables recording entirely (``track`` returns the null
handle 0; ``update``/``release`` on it are no-ops).
"""

from __future__ import annotations

import os
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from . import graftsched, grafttime

# Lock-discipline contract (tools/graftcheck locks pass): the entry
# table and the running totals are written by engine/scheduler threads
# and read by /debug/memory and /healthz handlers concurrently — all
# under the ledger instance's ``_lock``. Bus/gauge emission happens
# OUTSIDE the hold (the apparatus stays off its own critical section).
GUARDED_STATE = {"_entries": "_lock", "_component_totals": "_lock",
                 "_total": "_lock", "_peaks": "_lock"}
LOCK_ORDER = ("_lock",)

# Timeline contract (tools/graftcheck timeline pass): every byte delta
# lands on the unified causal stream — an OOM-shaped residency climb is
# only diagnosable when it sits on the same clock as the admissions and
# evictions that drove it.
TIMELINE_EVENTS = {
    "mem_alloc": "MemoryLedger._emit",
    "mem_free": "MemoryLedger._emit",
}

# THE component vocabulary (tools/graftcheck/memory.py rejects a
# MEMORY_LEDGER declaration whose component falls outside it — a new
# residency class is a reviewed vocabulary change, not an ad-hoc
# string). Keep in sync with the ARCHITECTURE.md taxonomy table.
MEMORY_COMPONENTS = {
    "params":       "model parameter tree (placed or host-staged)",
    "pool_codes":   "paged KV pool block-storage plane (KVBlockPool"
                    ".data — full-precision or quantized codes)",
    "pool_scales":  "quantized pool per-block f32 scales plane "
                    "(KVBlockPool.scales)",
    "engine_cache": "contiguous KV caches and in-flight decode "
                    "working views (engine / iterbatch batch state)",
    "spec_buffers": "speculative-decode device token buffers",
    "prefix_store": "prefix-cache store holdings (non-pool mode "
                    "deep-copied cache pytrees)",
    "host_spill":   "grafttier host-RAM spill store (demoted prefix "
                    "entries' raw block codes + scales as numpy)",
}

# snapshot() holdings-table bound: hottest entries first, truncation
# marked (the graftscope keys-table discipline — a silent cap would
# read as "everything shown" exactly when a leak mints too many)
HOLDINGS_CAPACITY = 64

_enabled = [os.environ.get("GRAFTMEM", "1") != "0"]


def enabled() -> bool:
    return _enabled[0]


def set_enabled(value: bool) -> bool:
    """Toggle recording (returns the previous value). Tests use this
    for disabled-path coverage; production leaves it on."""
    prev = _enabled[0]
    _enabled[0] = bool(value)
    return prev


def measure(value: Any) -> Tuple[int, Dict[str, int]]:
    """Total live bytes and per-device attribution for one holding:
    the sum of ``leaf.nbytes`` over the pytree's array leaves — the
    buffers jax actually committed, never shape arithmetic. Per-device
    attribution comes from each leaf's ``addressable_shards`` when the
    runtime exposes them (a sharded leaf attributes each shard's bytes
    to its device); leaves without shard info attribute their full
    ``nbytes`` to ``"unsharded"``."""
    import jax  # deferred: the ledger must import before any backend

    total = 0
    devices: Dict[str, int] = {}
    for leaf in jax.tree_util.tree_leaves(value):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            continue
        total += int(nbytes)
        shards = getattr(leaf, "addressable_shards", None)
        attributed = False
        if shards:
            try:
                for sh in shards:
                    data = getattr(sh, "data", None)
                    sb = getattr(data, "nbytes", None)
                    if sb is None:
                        continue
                    dev = str(getattr(sh, "device", "unsharded"))
                    devices[dev] = devices.get(dev, 0) + int(sb)
                    attributed = True
            except Exception:  # noqa: BLE001 — attribution is
                attributed = False  # best-effort; totals are not
        if not attributed:
            devices["unsharded"] = devices.get("unsharded", 0) + int(nbytes)
    return total, devices


class MemoryLedger:
    """The process-wide byte ledger: a handle-keyed entry table (one
    entry per tracked holding instance — concurrent generates on one
    engine each hold their own working-cache entry without collision)
    plus independently maintained running per-component and grand
    totals (the redundancy IS the conservation check)."""

    def __init__(self):
        self._lock = graftsched.lock("graftmem.MemoryLedger._lock")
        # handle -> {"owner_id", "owner", "holding", "component",
        #            "bytes", "devices"}
        self._entries: Dict[int, dict] = {}
        # running totals, maintained incrementally on every mutation —
        # deliberately NOT derived from the entry table, so snapshot()
        # can cross-check the two bookkeeping paths (conservation)
        self._component_totals: Dict[str, int] = {}
        self._total = 0
        # component -> [peak_bytes, t_ms_at_peak]; "" keys the grand
        # total's peak
        self._peaks: Dict[str, list] = {}
        self._next_handle = 1
        self.t0 = time.perf_counter()

    # -- recording -----------------------------------------------------------

    def _now_ms(self) -> float:
        return (time.perf_counter() - self.t0) * 1e3

    def _emit(self, component: str, delta: int, comp_total: int,
              total: int) -> None:
        # outside the ledger lock by construction (callers compute the
        # deltas under the hold, then emit). The graftscope sample
        # mirrors onto grafttime as ``occupancy`` itself; the byte
        # delta additionally lands as its own mem_* event so replay
        # and Perfetto see allocation CAUSALITY, not just the series.
        from . import graftscope
        from .metrics import REGISTRY
        if delta >= 0:
            grafttime.emit("mem_alloc", component=component,
                           bytes=int(delta), total=int(comp_total))
        else:
            grafttime.emit("mem_free", component=component,
                           bytes=-int(delta), total=int(comp_total))
        graftscope.sample("hbm_bytes", float(comp_total),
                          component=component)
        REGISTRY.gauge("hbm_bytes", float(comp_total),
                       component=component)
        REGISTRY.gauge("hbm_bytes", float(total), component="total")

    def track(self, owner: Any, holding: str, component: str,
              value: Any) -> int:
        """Register one long-lived device holding; returns the entry's
        handle (0 when disabled). ``component`` must be in
        :data:`MEMORY_COMPONENTS` (the static pass verifies call sites;
        the runtime check catches dynamic drift). The owner is held
        weakly — a GC'd owner's entries auto-release."""
        if not _enabled[0]:
            return 0
        if component not in MEMORY_COMPONENTS:
            raise ValueError(
                f"component {component!r} outside the graftmem "
                f"vocabulary {sorted(MEMORY_COMPONENTS)}")
        nbytes, devices = measure(value)
        with self._lock:
            handle = self._next_handle
            self._next_handle += 1
            self._entries[handle] = {
                "owner_id": id(owner),
                "owner": type(owner).__name__,
                "holding": holding,
                "component": component,
                "bytes": nbytes,
                "devices": devices,
            }
            comp_total = self._component_totals.get(component, 0) + nbytes
            self._component_totals[component] = comp_total
            self._total += nbytes
            total = self._total
            self._note_peaks_locked(component, comp_total, total)
        try:
            weakref.finalize(owner, self.release, handle)
        except TypeError:
            pass  # non-weakref-able owner: explicit release only
        self._emit(component, nbytes, comp_total, total)
        return handle

    def update(self, handle: int, value: Any) -> None:
        """Re-measure a rebound holding (pool buffer through a donated
        mover, batch cache through grow/admit) against the same entry."""
        if not _enabled[0] or not handle:
            return
        nbytes, devices = measure(value)
        with self._lock:
            entry = self._entries.get(handle)
            if entry is None:
                return
            delta = nbytes - entry["bytes"]
            entry["bytes"] = nbytes
            entry["devices"] = devices
            component = entry["component"]
            comp_total = self._component_totals.get(component, 0) + delta
            self._component_totals[component] = comp_total
            self._total += delta
            total = self._total
            self._note_peaks_locked(component, comp_total, total)
        if delta:
            self._emit(component, delta, comp_total, total)

    def release(self, handle: int) -> None:
        """Retire one holding (idempotent — the weakref finalizer and
        an explicit release may both fire)."""
        if not handle:
            return
        with self._lock:
            entry = self._entries.pop(handle, None)
            if entry is None:
                return
            nbytes = entry["bytes"]
            component = entry["component"]
            comp_total = self._component_totals.get(component, 0) - nbytes
            self._component_totals[component] = comp_total
            self._total -= nbytes
            total = self._total
        if nbytes:
            self._emit(component, -nbytes, comp_total, total)

    def _note_peaks_locked(self, component: str, comp_total: int,
                           total: int) -> None:
        now = self._now_ms()
        peak = self._peaks.get(component)
        if peak is None or comp_total > peak[0]:
            self._peaks[component] = [comp_total, round(now, 3)]
        gpeak = self._peaks.get("")
        if gpeak is None or total > gpeak[0]:
            self._peaks[""] = [total, round(now, 3)]

    # -- reading -------------------------------------------------------------

    def component_bytes(self) -> Dict[str, int]:
        """Per-component live bytes, derived from the entry table (the
        bookkeeping path conservation checks AGAINST the running
        totals)."""
        with self._lock:
            out: Dict[str, int] = {}
            for entry in self._entries.values():
                c = entry["component"]
                out[c] = out.get(c, 0) + entry["bytes"]
            return out

    def total_bytes(self) -> int:
        with self._lock:
            return int(self._total)

    def peak_bytes(self) -> int:
        with self._lock:
            peak = self._peaks.get("")
            return peak[0] if peak else 0

    def holding_bytes(self, owner: Any, holding: str) -> int:
        """Live bytes of one owner's named holding (sum over its
        entries) — what /healthz derives ``pool_bytes`` from, so pool
        byte reporting has exactly ONE bookkeeping path."""
        oid = id(owner)
        with self._lock:
            return sum(e["bytes"] for e in self._entries.values()
                       if e["owner_id"] == oid
                       and e["holding"] == holding)

    def snapshot(self) -> dict:
        """Bounded JSON view (the /debug/memory payload body): the
        per-component table with peaks, per-device attribution, the
        hottest holdings, and the conservation verdict."""
        with self._lock:
            derived: Dict[str, int] = {}
            devices: Dict[str, int] = {}
            holdings: List[dict] = []
            for entry in self._entries.values():
                c = entry["component"]
                derived[c] = derived.get(c, 0) + entry["bytes"]
                for dev, b in entry["devices"].items():
                    devices[dev] = devices.get(dev, 0) + b
                holdings.append({
                    "component": c,
                    "holding": entry["holding"],
                    "owner": entry["owner"],
                    "bytes": entry["bytes"],
                })
            running = {c: b for c, b in self._component_totals.items()
                       if b or derived.get(c)}
            total = self._total
            entries_n = len(self._entries)
            peaks = {(c or "total"): {"bytes": p[0], "t_ms": p[1]}
                     for c, p in self._peaks.items()}
        conserved = (derived == running
                     and sum(running.values()) == total)
        holdings.sort(key=lambda h: h["bytes"], reverse=True)
        components = {
            c: {"bytes": running.get(c, 0),
                "entries": sum(1 for h in holdings
                               if h["component"] == c),
                "peak_bytes": peaks.get(c, {}).get("bytes", 0)}
            for c in sorted(set(running) | set(derived))}
        out = {
            "enabled": enabled(),
            # the honesty header (the utils.tracing contract): what
            # these numbers are and are not
            "truth": ("bytes are live jax buffer nbytes summed over "
                      "REGISTERED holdings (the MEMORY_LEDGER "
                      "contract) — transient activations and XLA "
                      "scratch are not ledger entries; per-device "
                      "attribution uses addressable_shards where the "
                      "runtime exposes them"),
            "components": components,
            "total_bytes": total,
            "peak_bytes": peaks.get("total", {}).get("bytes", 0),
            "peaks": peaks,
            "devices": devices,
            "entries": entries_n,
            "holdings": holdings[:HOLDINGS_CAPACITY],
            "conserved": conserved,
        }
        if len(holdings) > HOLDINGS_CAPACITY:
            out["holdings_truncated"] = True
        return out

    def reconcile(self, plan_row) -> dict:
        """Drift between the cost model's predicted footprint and the
        ledger's live bytes (graftscope's measured-vs-modeled pattern,
        applied to bytes). ``plan_row`` is a ``costmodel.PlanRow`` or
        its ``to_dict()`` — predicted ``param_bytes_per_device`` and
        ``kv_bytes_per_device`` compare against the ledger's ``params``
        and ``pool_codes``+``pool_scales`` components. Ratios are
        measured/predicted; on a single-device process the ledger total
        IS per-device, which is what the CPU exactness pins exercise.
        A quantized pool drifts BELOW the f32-aval prediction by
        design — reconcile reports it, the capacity bench journals it."""
        row = (plan_row.to_dict() if hasattr(plan_row, "to_dict")
               else dict(plan_row))
        comp = self.component_bytes()
        measured_params = comp.get("params", 0)
        measured_pool = (comp.get("pool_codes", 0)
                         + comp.get("pool_scales", 0))
        measured_cache = comp.get("engine_cache", 0)

        def _cmp(measured: int, predicted) -> dict:
            predicted = int(predicted or 0)
            out = {"measured_bytes": measured,
                   "predicted_bytes": predicted}
            if predicted > 0:
                ratio = measured / predicted
                out["ratio"] = round(ratio, 6)
                out["drift"] = round(abs(ratio - 1.0), 6)
            return out

        components = {
            "params": _cmp(measured_params,
                           row.get("param_bytes_per_device")),
            "kv": _cmp(measured_pool or measured_cache,
                       row.get("kv_bytes_per_device")),
        }
        total_measured = self.total_bytes()
        out = {
            "plan": row.get("label"),
            "components": components,
            "total": _cmp(total_measured,
                          row.get("hbm_bytes_per_device")),
            "ledger": comp,
        }
        drifts = [c["drift"] for c in components.values()
                  if "drift" in c]
        if drifts:
            out["max_component_drift"] = max(drifts)
        return out

    # -- test isolation (tests/conftest.py) ----------------------------------

    def dump_state(self) -> tuple:
        with self._lock:
            return (dict(self._entries),
                    dict(self._component_totals),
                    self._total,
                    {k: list(v) for k, v in self._peaks.items()},
                    self._next_handle, self.t0)

    def restore_state(self, state: tuple) -> None:
        entries, totals, total, peaks, next_handle, t0 = state
        with self._lock:
            self._entries = dict(entries)
            self._component_totals = dict(totals)
            self._total = total
            self._peaks = {k: list(v) for k, v in peaks.items()}
            # never rewind the handle counter: entries registered after
            # the dump vanish here, but their owners' finalizers may
            # still fire release(handle) later — a rewound counter would
            # hand the same id to a NEW entry and the stale finalizer
            # would free it (handles stay process-unique instead)
            self._next_handle = max(self._next_handle, next_handle)
            self.t0 = t0

    def clear(self) -> None:
        # _next_handle deliberately NOT rewound (see restore_state):
        # finalizers of owners created before the clear may still fire
        # release(handle), and a reused id would free the wrong entry
        with self._lock:
            self._entries = {}
            self._component_totals = {}
            self._total = 0
            self._peaks = {}
            self.t0 = time.perf_counter()


# process-wide default ledger (what the runtime modules and serving app
# register against; tests snapshot/restore it via the conftest fixture)
STATE = MemoryLedger()


# -- module-level conveniences (the call-site API the static pass scans) ------


def track(owner: Any, holding: str, component: str, value: Any) -> int:
    handle = STATE.track(owner, holding, component, value)
    # ledger registration is the one moment the VALUE itself is in hand,
    # so the live placement auditor (utils/graftshard, GRAFTSHARD=1)
    # piggybacks here; unarmed it is a single env-var check
    from . import graftshard
    graftshard.observe_track(owner, holding, component, value, handle)
    return handle


def update(handle: int, value: Any) -> None:
    STATE.update(handle, value)
    from . import graftshard
    graftshard.observe_update(handle, value)


def release(handle: int) -> None:
    STATE.release(handle)
    from . import graftshard
    graftshard.observe_release(handle)


def holding_bytes(owner: Any, holding: str) -> int:
    return STATE.holding_bytes(owner, holding)


def component_bytes() -> Dict[str, int]:
    return STATE.component_bytes()


def total_bytes() -> int:
    return STATE.total_bytes()


def peak_bytes() -> int:
    return STATE.peak_bytes()


def snapshot() -> dict:
    return STATE.snapshot()


def reconcile(plan_row) -> dict:
    return STATE.reconcile(plan_row)


def dump_state() -> tuple:
    return STATE.dump_state()


def restore_state(state: tuple) -> None:
    STATE.restore_state(state)


def clear() -> None:
    STATE.clear()
