"""In-process metrics: counters + latency histograms, Prometheus-exposable.

The reference's observability is one startup print and uvicorn access
logs (reference server.py:27, Dockerfile:19; SURVEY.md §5 "Metrics":
ABSENT — the optional k8s metrics-server only sees pod CPU/mem). This
registry backs the serving layer's /metrics endpoint and the decode
engine's per-request timings.

Thread-safe (the stdlib HTTP server is one-thread-per-request). Export
format is Prometheus text exposition, so a scrape config pointed at the
pod Just Works; ``snapshot()`` returns the same data as a dict for tests
and /healthz embedding.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Tuple

# latency buckets (seconds): 1ms .. 60s, roughly log-spaced
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._histograms: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                               List] = {}

    @staticmethod
    def _key(name: str, labels: Dict[str, str]):
        return name, tuple(sorted(labels.items()))

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = self._key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def observe(self, name: str, seconds: float, **labels) -> None:
        key = self._key(name, labels)
        with self._lock:
            if key not in self._histograms:
                self._histograms[key] = [
                    [0] * (len(DEFAULT_BUCKETS) + 1), 0.0, 0]
            counts, _, _ = self._histograms[key]
            counts[bisect.bisect_left(DEFAULT_BUCKETS, seconds)] += 1
            self._histograms[key][1] += seconds
            self._histograms[key][2] += 1

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {}
            for (name, labels), v in self._counters.items():
                out[_fmt_name(name, labels)] = v
            for (name, labels), (counts, total, n) in self._histograms.items():
                base = _fmt_name(name, labels)
                out[base + "_count"] = n
                out[base + "_sum"] = round(total, 6)
                if n:
                    out[base + "_avg"] = round(total / n, 6)
            return out

    def prometheus(self) -> str:
        """Prometheus text exposition format.

        One ``# TYPE`` line per metric *name* with all label sets grouped
        under it — duplicate TYPE lines for a name make the scraper drop
        the whole page.
        """
        lines: List[str] = []
        with self._lock:
            seen_type: set = set()
            for (name, labels), v in sorted(self._counters.items()):
                if name not in seen_type:
                    seen_type.add(name)
                    lines.append(f"# TYPE {name} counter")
                lines.append(f"{name}{_prom_labels(labels)} {v}")
            for (name, labels), (counts, total, n) in sorted(
                    self._histograms.items()):
                if name not in seen_type:
                    seen_type.add(name)
                    lines.append(f"# TYPE {name} histogram")
                acc = 0
                for bound, c in zip(DEFAULT_BUCKETS, counts):
                    acc += c
                    lines.append(
                        f'{name}_bucket{_prom_labels(labels, le=bound)} {acc}')
                acc += counts[-1]
                lines.append(
                    f'{name}_bucket{_prom_labels(labels, le="+Inf")} {acc}')
                lines.append(f"{name}_sum{_prom_labels(labels)} {total}")
                lines.append(f"{name}_count{_prom_labels(labels)} {n}")
        return "\n".join(lines) + "\n"


def _fmt_name(name: str, labels) -> str:
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


def _prom_labels(labels, le=None) -> str:
    items = list(labels)
    if le is not None:
        items = items + [("le", le)]
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"


# process-wide default registry (what serving.app uses)
REGISTRY = MetricsRegistry()
