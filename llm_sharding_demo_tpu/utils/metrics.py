"""In-process metrics: counters, gauges + latency histograms, Prometheus-exposable.

The reference's observability is one startup print and uvicorn access
logs (reference server.py:27, Dockerfile:19; SURVEY.md §5 "Metrics":
ABSENT — the optional k8s metrics-server only sees pod CPU/mem). This
registry backs the serving layer's /metrics endpoint and the decode
engine's per-request timings.

Thread-safe (the stdlib HTTP server is one-thread-per-request). Export
format is Prometheus text exposition, so a scrape config pointed at the
pod Just Works; ``snapshot()`` returns the same data as a dict for tests
and /healthz embedding.

``METRIC_CATALOG`` is the single inventory of every metric name this
codebase may emit, with its instrument kind. ``tools/check_metrics.py``
(run in the test suite) greps the ``REGISTRY.inc/observe/gauge`` call
sites against it, so a typo'd name cannot silently fork a time series.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Tuple

from . import graftsched

# Lock-discipline contract (tools/graftcheck locks pass): every series
# map and the compile-watch cursor live under the owning instance's
# ``_lock``; both classes are called from arbitrary handler/scheduler
# threads.
GUARDED_STATE = {"_counters": "_lock", "_gauges": "_lock",
                 "_histograms": "_lock", "_seen": "_lock"}
LOCK_ORDER = ("_lock",)

# latency buckets (seconds): 1ms .. 60s, roughly log-spaced
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


# name -> instrument kind ("counter" | "histogram" | "gauge"). THE metric
# inventory: every literal name passed to REGISTRY.inc/observe/gauge must
# appear here with the matching kind (tools/check_metrics.py enforces it),
# and docs/ARCHITECTURE.md's observability section points here instead of
# duplicating the list.
METRIC_CATALOG: Dict[str, str] = {
    # serving surface (serving/app.py)
    "generate_requests_total": "counter",
    "generated_tokens_total": "counter",
    "upstream_failures_total": "counter",
    "generate_request_seconds": "histogram",
    # request-phase latency split (derived from the request trace):
    # time-to-first-token and per-token (inter-token) time, per mode
    "ttft_seconds": "histogram",
    "tpot_seconds": "histogram",
    # graftscope device-time attribution (utils/graftscope.py):
    # per-dispatch wall clock of every PROFILED_SCOPES jit entry point,
    # labeled scope="module._entry" — serving-thread enqueue windows by
    # default, device truth under GRAFTSCOPE_SYNC=1 (see graftscope's
    # truth model); and the per-decode-step time each decode front end
    # derives from its own timing window, labeled by component
    # (component="engine": device-inclusive, the final fetch syncs;
    # component="iter"/"iter_spec": serving-thread dispatch view)
    "dispatch_seconds": "histogram",
    "decode_step_seconds": "histogram",
    # admission batcher (runtime/batcher.py)
    "decode_batches_total": "counter",
    "batched_requests_total": "counter",
    "batched_rows_padded_total": "counter",
    # iteration-level scheduler (runtime/iterbatch.py)
    "iter_batches_total": "counter",
    "iter_joins_total": "counter",
    "iter_segments_total": "counter",
    "iter_spec_segments_total": "counter",
    "iter_grows_total": "counter",
    "iter_eos_retires_total": "counter",
    "iter_rows_total": "counter",
    # speculation (runtime/spec_decode.py)
    "spec_verify_steps_total": "counter",
    "spec_emitted_tokens_total": "counter",
    # prefix cache (runtime/prefix_cache.py)
    "prefix_cache_hits_total": "counter",
    "prefix_cache_misses_total": "counter",
    "prefix_cache_reused_tokens_total": "counter",
    # compile events: one increment per NEW jitted program entering a
    # tracked cache (engine prefill/decode, spec loops/segments) — a
    # compile storm is visible as a burst here, distinguishable from
    # steady-state latency
    "compile_events_total": "counter",
    # paged KV pool (runtime/kv_pool.py)
    "kv_pool_evictions_total": "counter",   # LRU prefix-entry evictions
    "kv_pool_cow_copies_total": "counter",  # copy-on-write block copies
    "kv_pool_preemptions_total": "counter",  # rows parked under pressure
    "kv_pool_resumes_total": "counter",     # parked rows recomputed back in
    # serving admission control: /generate requests turned away with
    # 429 + Retry-After because the KV pool could not host them
    "kv_pool_admission_rejections_total": "counter",
    # fault tolerance (graftfault): shard-hop retries through the typed
    # HopPolicy, labeled stage (shard role) x low-cardinality failure
    # reason (timeout/connection/http_error/error); and transient
    # decode faults the iter scheduler absorbed by parking the live
    # rows through the recompute-resume path
    "shard_hop_retries_total": "counter",
    "iter_fault_parks_total": "counter",
    # SLO deadline misses (graftload / loadgen SLO_SOURCE_METRICS):
    # accepted requests that exhausted their X-Deadline-Ms budget and
    # died typed (503 deadline_exceeded) — the source series behind
    # every declared ``deadline_miss`` SLO target, and deliberately
    # NOT a shed counter (sheds refuse work; this broke a promise).
    # Counts EVERY budget death: the server cannot see caller intent,
    # so deliberate walk-aways (the loadgen abandonment profile's
    # short budgets) increment it too — the load harness nets those
    # out CLIENT-side when scoring deadline_miss SLOs
    # (loadgen.driver.summarize), so alert thresholds on this raw
    # series must budget for expected abandonment traffic.
    "deadline_misses_total": "counter",
    # live-state gauges
    "queue_depth": "gauge",                 # waiting requests per scheduler
    # per-TARGET circuit-breaker state (graftfault HopPolicy): 1 while
    # that downstream's breaker is OPEN, 0 when a probe closes it. The
    # target label names the breaker's downstream — a stage shard on
    # the coordinator, a replica name on the fleet router (N
    # downstreams, one breaker and one labeled series each). Emitted
    # as a REGISTRY gauge AND sampled into the graftscope occupancy
    # series on transitions, so a graftload run sees breaker flaps on
    # the same timeline as queue depth.
    "hop_breaker_open": "gauge",
    # graftfleet router (serving/router.py): request routing per
    # target/role, affinity accounting (ring-owner routes vs fallback
    # placements), typed per-replica sheds encountered walking the
    # candidate list (whether fallback absorbed them or the shed was
    # surfaced), and prefill hops that degraded to a cold decode-side
    # prefill
    "fleet_requests_total": "counter",
    "fleet_affinity_hits_total": "counter",
    "fleet_affinity_fallbacks_total": "counter",
    "fleet_sheds_total": "counter",
    "fleet_prefill_degraded_total": "counter",
    "batch_occupancy": "gauge",             # live rows / compiled width
    "iter_live_rows": "gauge",              # live iterbatch rows
    # KV memory in BLOCK denomination, labeled by the writer component
    # (component="pool"/"paged"/"iter": exact allocator numbers;
    # component="engine"/"batcher": the contiguous arena expressed in
    # equivalent blocks via kv_block_gauges) — one unit across the
    # whole serving surface, so "how full is KV memory" is one query.
    # Replaces the retired per-component kv_cache_slots_in_use series
    # (see RETIRED_METRICS).
    # Pool-backed components additionally label the pair with
    # block_dtype (the storage regime: f32/bf16 full-precision, or
    # int8/fp8 quantized — runtime.kv_pool) so a capacity query can
    # group by what a block IS, and publish the per-block HBM cost:
    # quantized pools fit 2-4x the blocks in the same bytes, and the
    # gauge pair alone would misread that as "more memory".
    "kv_cache_blocks_in_use": "gauge",
    "kv_cache_blocks_total": "gauge",
    "kv_pool_bytes_per_block": "gauge",
    # host-RAM KV spill tier (runtime/kv_tier.py — grafttier): demotions
    # move a cold zero-ref prefix entry's raw blocks (codes + scales for
    # quantized pools) to bounded host buffers instead of evicting to
    # oblivion; promotions device_put them back on an affinity hit. The
    # gauge pair is the host tier's block occupancy in the SAME block
    # denomination as the device pair above (host blocks hold the same
    # bytes a device block does), so prefix-store depth across tiers is
    # one query.
    "tier_demotions_total": "counter",
    "tier_promotions_total": "counter",
    "kv_host_blocks_in_use": "gauge",
    "kv_host_blocks_total": "gauge",
    "jit_program_cache_size": "gauge",      # compiled programs per component
    "spec_acceptance_rate": "gauge",        # emitted tokens per verify
    # continuous planning (utils/graftwatch.py): one increment per live
    # plan switch (labeled from/to — the certified set is tiny, so the
    # label space is bounded by construction), and a per-plan 0/1 gauge
    # naming the ACTIVE plan. The gauge doubles as a graftscope
    # occupancy series, so a graftload run sees plan switches on the
    # same timeline as queue depth and pool blocks.
    "plan_switches_total": "counter",
    "auto_plan_active": "gauge",
    # declared HBM ledger (utils/graftmem.py): live registered device
    # bytes, labeled component= from the MEMORY_COMPONENTS vocabulary
    # (params / pool_codes / pool_scales / engine_cache / spec_buffers
    # / prefix_store, plus the "total" grand sum). The gauge doubles
    # as a graftscope occupancy series, so residency trajectories sit
    # beside queue depth and pool blocks; /debug/memory serves the
    # full per-holding table.
    "hbm_bytes": "gauge",
    # trend & drift watch (utils/grafttrend.py): one increment per
    # WATCH_POLICY trip, labeled watch x severity (both drawn from the
    # declared policy, so the label space is bounded by construction);
    # and the live-refit output — the ICI byte weight currently
    # threaded into plan scoring (a-priori costmodel.ICI_BYTE_WEIGHT
    # until the first grafttrend.refit, the fitted value after). The
    # gauge doubles as a graftscope occupancy series, so weight moves
    # sit on the same timeline as queue depth and plan switches.
    "trend_alerts_total": "counter",
    "costmodel_byte_weight": "gauge",
}

# Metric names that USED to exist and were replaced: a call site (or a
# catalog entry) reviving one of these fails the graftcheck
# metric-catalog rule with the replacement spelled out — dashboards
# migrated once and must not silently fork back to the dead series.
RETIRED_METRICS: Dict[str, str] = {
    "kv_cache_slots_in_use":
        "kv_cache_blocks_in_use / kv_cache_blocks_total (block "
        "denomination, same component labels)",
}

# Block width used to express contiguous (non-pooled) KV arenas in the
# pool's block denomination — and runtime.kv_pool's default physical
# block size, so the two denominations agree by default.
DEFAULT_KV_BLOCK_SIZE = 16


def kv_block_gauges(component: str, used_slots: int, total_slots: int,
                    block_size: int = DEFAULT_KV_BLOCK_SIZE,
                    registry: "MetricsRegistry" = None) -> None:
    """Set the ``kv_cache_blocks_*`` gauge pair for a component that
    manages contiguous slot arenas (solo engine, admission batcher,
    non-pooled iterbatch): slots are converted to equivalent blocks
    (ceil). Pool-backed components bypass this and publish the
    allocator's exact numbers (``KVBlockPool.note_gauges``)."""
    reg = registry or REGISTRY
    reg.gauge("kv_cache_blocks_in_use",
              -(-int(used_slots) // block_size) if used_slots > 0 else 0,
              component=component)
    reg.gauge("kv_cache_blocks_total",
              -(-int(total_slots) // block_size) if total_slots > 0 else 0,
              component=component)


class MetricsRegistry:
    def __init__(self):
        self._lock = graftsched.lock("metrics.MetricsRegistry._lock")
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._histograms: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                               List] = {}

    @staticmethod
    def _key(name: str, labels: Dict[str, str]):
        return name, tuple(sorted(labels.items()))

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = self._key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def gauge(self, name: str, value: float, **labels) -> None:
        """Set a gauge to its current value (last write wins)."""
        key = self._key(name, labels)
        with self._lock:
            self._gauges[key] = float(value)

    def observe(self, name: str, seconds: float, **labels) -> None:
        key = self._key(name, labels)
        with self._lock:
            if key not in self._histograms:
                self._histograms[key] = [
                    [0] * (len(DEFAULT_BUCKETS) + 1), 0.0, 0]
            counts, _, _ = self._histograms[key]
            counts[bisect.bisect_left(DEFAULT_BUCKETS, seconds)] += 1
            self._histograms[key][1] += seconds
            self._histograms[key][2] += 1

    # -- test isolation (tests/conftest.py) ----------------------------------

    def dump_state(self) -> tuple:
        """Deep snapshot of all series — the conftest isolation fixture
        pairs this with ``restore_state`` so one test's metric writes
        cannot leak into another's assertions on the process-global
        ``REGISTRY``."""
        with self._lock:
            return (dict(self._counters), dict(self._gauges),
                    {k: [list(v[0]), v[1], v[2]]
                     for k, v in self._histograms.items()})

    def restore_state(self, state: tuple) -> None:
        counters, gauges, histograms = state
        with self._lock:
            self._counters = dict(counters)
            self._gauges = dict(gauges)
            self._histograms = {k: [list(v[0]), v[1], v[2]]
                                for k, v in histograms.items()}

    def histogram_buckets(self) -> Dict[str, tuple]:
        """``{name{k=v,...}: (bucket_counts, sum, count)}`` — the raw
        per-label-set bucket counts behind each histogram (bucket ``i``
        spans ``(DEFAULT_BUCKETS[i-1], DEFAULT_BUCKETS[i]]``, plus the
        +Inf overflow slot). ``snapshot()`` deliberately flattens
        histograms to count/sum/avg; the grafttrend burn-rate poller
        needs the bucket resolution to count observations past a
        declared SLO target without storing per-sample values."""
        with self._lock:
            return {_fmt_name(name, labels): (list(counts), total, n)
                    for (name, labels), (counts, total, n)
                    in self._histograms.items()}

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {}
            for (name, labels), v in self._counters.items():
                out[_fmt_name(name, labels)] = v
            for (name, labels), v in self._gauges.items():
                out[_fmt_name(name, labels)] = v
            for (name, labels), (counts, total, n) in self._histograms.items():
                base = _fmt_name(name, labels)
                out[base + "_count"] = n
                out[base + "_sum"] = round(total, 6)
                if n:
                    out[base + "_avg"] = round(total / n, 6)
            return out

    def prometheus(self) -> str:
        """Prometheus text exposition format.

        One ``# TYPE`` line per metric *name* with all label sets grouped
        under it — duplicate TYPE lines for a name make the scraper drop
        the whole page.
        """
        lines: List[str] = []
        with self._lock:
            seen_type: set = set()
            for (name, labels), v in sorted(self._counters.items()):
                if name not in seen_type:
                    seen_type.add(name)
                    lines.append(f"# TYPE {name} counter")
                lines.append(f"{name}{_prom_labels(labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                if name not in seen_type:
                    seen_type.add(name)
                    lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name}{_prom_labels(labels)} {v}")
            for (name, labels), (counts, total, n) in sorted(
                    self._histograms.items()):
                if name not in seen_type:
                    seen_type.add(name)
                    lines.append(f"# TYPE {name} histogram")
                acc = 0
                for bound, c in zip(DEFAULT_BUCKETS, counts):
                    acc += c
                    lines.append(
                        f'{name}_bucket{_prom_labels(labels, le=bound)} {acc}')
                acc += counts[-1]
                lines.append(
                    f'{name}_bucket{_prom_labels(labels, le="+Inf")} {acc}')
                lines.append(f"{name}_sum{_prom_labels(labels)} {total}")
                lines.append(f"{name}_count{_prom_labels(labels)} {n}")
        return "\n".join(lines) + "\n"


class CompileWatch:
    """Turns jitted-program cache growth into ``compile_events_total``.

    Wraps one ``jax.jit`` result; ``check()`` (called after invocations,
    off the hot device path) diffs ``_cache_size()`` against the last
    observed value and increments the counter by exactly the number of
    NEW compiled programs, labeled with ``phase`` — so a compile storm
    (e.g. unbucketed shapes minting a program per request) is visible as
    a counter burst, distinguishable from steady-state latency.
    """

    def __init__(self, phase: str, fn):
        self.phase = phase
        self._fn = fn
        self._seen = 0
        # solo-mode engines are called straight from server handler
        # threads — an unsynchronized read-modify-write of _seen would
        # let two concurrent checks double-count the same new program
        self._lock = graftsched.lock("metrics.CompileWatch._lock")

    def seen(self) -> int:
        """Programs observed so far (locked read — gauge derivations in
        engine/spec_decode run on handler threads concurrent with
        ``check``)."""
        with self._lock:
            return self._seen

    def check(self, registry: "MetricsRegistry" = None) -> int:
        size_of = getattr(self._fn, "_cache_size", None)
        if size_of is None:  # non-jit stub (tests)
            return 0
        size = size_of()
        with self._lock:
            new = size - self._seen
            if new > 0:
                self._seen = size
        if new > 0:
            (registry or REGISTRY).inc("compile_events_total", value=new,
                                       phase=self.phase)
        return max(new, 0)


def _fmt_name(name: str, labels) -> str:
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


def _escape_label_value(v) -> str:
    """Escape a label value per the Prometheus text-format spec:
    backslash, double-quote, and line-feed must be escaped, or the
    exposition line is invalid and the scraper drops the WHOLE page."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels, le=None) -> str:
    items = list(labels)
    if le is not None:
        items = items + [("le", le)]
    if not items:
        return ""
    return "{" + ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in items) + "}"


# process-wide default registry (what serving.app uses)
REGISTRY = MetricsRegistry()
