"""Tracing: request-scoped span trees + ``jax.profiler`` helpers.

SURVEY.md §5 "Tracing / profiling": the reference imports ``time`` and
never uses it (reference server.py:3). Two layers here:

**Profiler helpers** (device-level, attach-a-tool workflows):

- ``trace(dir)``: context manager capturing an XLA/TPU profile viewable
  in TensorBoard/Perfetto (device timelines, HLO cost, HBM traffic);
- ``annotate(name)``: named span that shows up inside those traces
  (``jax.profiler.TraceAnnotation``);
- ``timed(name)``: lightweight host-side wall-clock span recording into
  ``utils.metrics.REGISTRY`` — per-request numbers /metrics exposes.
  ``timed(..., sync=True)`` additionally ``block_until_ready``s the
  value the body hands to ``handle.sync(...)`` before closing the
  window: DEVICE truth instead of the async-dispatch enqueue window
  (utils.graftscope's attribution mode uses it; serving never does).

**Request traces** (always-on, no profiler attached): every /generate
request carries a ``RequestTrace`` — a tree of timed spans (tokenize →
queue wait → prefill → decode segments → detokenize) annotated with
labels (mode, batch width, prefix hit depth, spec acceptance). The
serving layer derives TTFT/TPOT histograms from it and keeps the last N
completed traces in the ``FlightRecorder`` served at ``GET
/debug/requests``, so a slow request is diagnosable after the fact
without a profiler in the loop.

Propagation: the ambient trace rides a ``contextvars.ContextVar`` set by
``use_trace`` — runtime modules record through the module-level ``span``
/ ``record`` helpers, which no-op when no trace is active (zero cost off
the serving path). Batch schedulers run device work for MANY requests on
one worker thread; they wrap shared phases in ``use_trace(fanout(
traces))`` so one measured span lands in every participating request's
tree.

Span timestamps are ``time.perf_counter`` values; serialized timelines
are relative to the request's start. Scheduler-side decode spans measure
dispatch wall time (segments queue asynchronously on the device), which
is the honest serving-thread view — device-level truth is the profiler
trace's job.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from collections import deque
from typing import Iterator, List, Optional

from . import graftsched, grafttime

# Lock-discipline contract (tools/graftcheck locks pass): a trace's
# committed root spans and the flight recorder's ring are the only
# cross-thread mutable state here (open-span stacks are thread-local by
# design); both live under their instance's ``_lock`` — including the
# fanout commit, which appends to OTHER traces' span lists under each
# target's own lock.
GUARDED_STATE = {"spans": "_lock", "_traces": "_lock"}
LOCK_ORDER = ("_lock",)

# Timeline contract (tools/graftcheck timeline pass): every span lands
# on the unified causal stream (utils/grafttime) — open at entry, close
# with its measured window — correlated by the owning trace's
# X-Request-ID (fanout spans carry every participating rid).
TIMELINE_EVENTS = {
    "span_open": "_TraceSink.span",
    "span_close": "_TraceSink.span / add_span / RequestTrace.finish",
}


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a device-level profiler trace into ``log_dir``."""
    import jax
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span visible in profiler traces (device + host timelines)."""
    import jax
    return jax.profiler.TraceAnnotation(name)


class _TimedHandle:
    """What ``timed`` yields: hand ``sync(value)`` the dispatch result
    to opt that value into the window's close (device truth when the
    ``sync=`` mode is armed); ``seconds`` carries the measured duration
    after the block exits (graftscope's ring reads it)."""

    __slots__ = ("seconds", "_sync_value", "_armed")

    def __init__(self, armed: bool):
        self._armed = armed
        self._sync_value = None
        self.seconds = 0.0

    def sync(self, value):
        self._sync_value = value
        return value


@contextlib.contextmanager
def timed(name: str, registry=None, sync: bool = False,
          **labels) -> Iterator[_TimedHandle]:
    """Wall-clock span recorded as a histogram observation.

    Truth model: jax dispatch is ASYNC, so by default the window closes
    when the body returns — i.e. when the device work was ENQUEUED (the
    honest serving-thread view; the device may still be executing, so
    device time is silently undercounted). ``sync=True`` closes the
    window only after ``jax.block_until_ready`` on the value the body
    registered via ``handle.sync(...)`` — device truth, at the price of
    a blocking host sync per window (graftscope's attribution runs use
    it; the serving path never does). Both behaviors are pinned by
    tests/test_observability.py.
    """
    from .metrics import REGISTRY
    reg = registry if registry is not None else REGISTRY
    h = _TimedHandle(bool(sync))
    t0 = time.perf_counter()
    body_ok = False
    try:
        yield h
        body_ok = True
    finally:
        if body_ok and h._armed and h._sync_value is not None:
            # only after a SUCCESSFUL body: a body exception must
            # propagate unmasked, not be replaced by whatever a
            # poisoned in-flight computation raises from the sync
            import jax
            jax.block_until_ready(h._sync_value)
        h.seconds = time.perf_counter() - t0
        reg.observe(name, h.seconds, **labels)


# -- request-scoped span trees -----------------------------------------------


def new_request_id() -> str:
    return uuid.uuid4().hex[:12]


class Span:
    """One timed node: name, [t0, t1) perf_counter window, labels,
    children. Append-only while open; read-only once closed."""

    __slots__ = ("name", "t0", "t1", "labels", "children")

    def __init__(self, name: str, t0: float, t1: Optional[float] = None,
                 labels: Optional[dict] = None):
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.labels = dict(labels) if labels else {}
        self.children: List["Span"] = []

    @property
    def duration(self) -> float:
        return (self.t1 if self.t1 is not None else self.t0) - self.t0

    def to_dict(self, origin: float) -> dict:
        d = {"name": self.name,
             "start_ms": round((self.t0 - origin) * 1e3, 3),
             "duration_ms": round(self.duration * 1e3, 3)}
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.children:
            d["spans"] = [c.to_dict(origin) for c in self.children]
        return d


class _TraceSink:
    """Span-tree recording shared by ``RequestTrace`` and ``fanout``.

    Nesting is per-thread (a thread-local open-span stack guarded by a
    lock for the cross-thread ``add_span`` form), so a scheduler thread
    adding spans to a caller thread's trace lands them at the root — the
    right shape, since the two threads' phases don't enclose each other.
    """

    def __init__(self):
        self._lock = graftsched.lock("tracing._TraceSink._lock")
        self._tls = threading.local()
        self.spans: List[Span] = []

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _rid(self):
        """This sink's timeline correlation: the owning request's id
        (a fanout returns every target's — the shared-phase analog);
        the bare sink has none."""
        return getattr(self, "request_id", None)

    def _commit(self, span: Span) -> None:
        stack = self._stack()
        if stack:
            stack[-1].children.append(span)
        else:
            with self._lock:
                self.spans.append(span)

    @contextlib.contextmanager
    def span(self, name: str, **labels) -> Iterator[Span]:
        s = Span(name, time.perf_counter(), labels=labels)
        stack = self._stack()
        stack.append(s)
        grafttime.emit("span_open", name=name, rid=self._rid(), t=s.t0)
        try:
            yield s
        finally:
            s.t1 = time.perf_counter()
            stack.pop()
            self._commit(s)
            grafttime.emit("span_close", name=name, rid=self._rid(),
                           t=s.t1, dur_ms=round(s.duration * 1e3, 3))

    def add_span(self, name: str, t0: float, t1: float, **labels) -> Span:
        """Record an already-timed span (schedulers time phases once and
        attach them to every participating request)."""
        s = Span(name, t0, t1, labels=labels)
        self._commit(s)
        grafttime.emit("span_close", name=name, rid=self._rid(), t=t1,
                       dur_ms=round(s.duration * 1e3, 3))
        return s

    def event(self, name: str, **labels) -> Span:
        now = time.perf_counter()
        return self.add_span(name, now, now, **labels)


class RequestTrace(_TraceSink):
    """The span tree of one request, plus identity and summary fields."""

    def __init__(self, request_id: Optional[str] = None, **labels):
        super().__init__()
        self.request_id = request_id or new_request_id()
        self.labels = dict(labels)
        self.t0 = time.perf_counter()
        self.started_unix = time.time()
        self.t1: Optional[float] = None

    def finish(self) -> "RequestTrace":
        if self.t1 is None:
            self.t1 = time.perf_counter()
            # the request's terminal timeline event: the whole-request
            # window closing (the "final span close" a /debug/timeline
            # ?rid= stream ends on)
            grafttime.emit("span_close", name="request",
                           rid=self.request_id, t=self.t1,
                           dur_ms=round(self.duration * 1e3, 3))
        return self

    @property
    def duration(self) -> float:
        return (self.t1 if self.t1 is not None
                else time.perf_counter()) - self.t0

    def find(self, name: str) -> Optional[Span]:
        """First span with ``name``, depth-first."""
        def walk(spans):
            for s in spans:
                if s.name == name:
                    return s
                got = walk(s.children)
                if got is not None:
                    return got
            return None
        with self._lock:
            return walk(self.spans)

    def find_all(self, name: str) -> List[Span]:
        out: List[Span] = []

        def walk(spans):
            for s in spans:
                if s.name == name:
                    out.append(s)
                walk(s.children)
        with self._lock:
            walk(self.spans)
        return out

    def graft(self, name: str, payload: Optional[dict], t0: float,
              t1: float, **labels) -> Span:
        """Join a downstream replica's serialized trace (its
        ``to_dict`` payload, fetched by the propagated X-Request-ID)
        into THIS trace as a hop span over ``[t0, t1)`` whose children
        are the replica's own spans — the fleet router's cross-replica
        stitch, so ``/debug/requests`` shows ONE tree per request with
        the hop visible. The replica's relative timeline is re-based
        onto the hop start (same-process clocks in the harness; across
        real processes the skew is the hop's queueing, which is
        exactly what the offset shows). ``payload=None`` (recorder
        missing, ring entry evicted) degrades to a bare hop span."""
        hop = Span(name, t0, t1, labels=labels)
        if payload is not None:
            hop.labels.setdefault("replica_request_id",
                                  payload.get("request_id"))
            hop.children = [span_from_dict(c, t0)
                            for c in payload.get("spans", ())]
        self._commit(hop)
        return hop

    def to_dict(self) -> dict:
        with self._lock:
            spans = [s.to_dict(self.t0) for s in self.spans]
        d = {"request_id": self.request_id,
             "started_unix": round(self.started_unix, 3),
             "duration_ms": round(self.duration * 1e3, 3),
             "spans": spans}
        if self.labels:
            d["labels"] = dict(self.labels)
        return d


def span_from_dict(d: dict, base: float) -> Span:
    """Rebuild a serialized span (a ``Span.to_dict`` payload) as a live
    Span re-based onto ``base`` (a local perf_counter instant) — the
    cross-replica stitch's unit: a downstream replica's relative-ms
    timeline becomes spans on THIS process's clock, child shape
    preserved."""
    t0 = base + d.get("start_ms", 0.0) / 1e3
    s = Span(d.get("name", "?"), t0,
             t0 + d.get("duration_ms", 0.0) / 1e3,
             labels=d.get("labels"))
    s.children = [span_from_dict(c, base) for c in d.get("spans", ())]
    return s


class _FanoutTrace(_TraceSink):
    """Records spans once and commits each completed root to every target
    trace — how a batch scheduler attributes one shared device phase
    (prefill, a decode round) to all rows riding it. Nested spans inside
    the fanout keep their tree shape; the shared Span objects are
    read-only after commit, so sharing across traces is safe."""

    def __init__(self, traces: List[RequestTrace]):
        super().__init__()
        self._targets = [t for t in traces if t is not None]

    def _rid(self):
        # one shared phase, every participating request's stream
        return tuple(t.request_id for t in self._targets)

    def _commit(self, span: Span) -> None:
        stack = self._stack()
        if stack:
            stack[-1].children.append(span)
            return
        for t in self._targets:
            with t._lock:
                t.spans.append(span)


def fanout(traces: List[Optional[RequestTrace]]) -> _FanoutTrace:
    return _FanoutTrace(traces)


_current: "contextvars.ContextVar[Optional[_TraceSink]]" = \
    contextvars.ContextVar("request_trace", default=None)


def current_trace() -> Optional[_TraceSink]:
    return _current.get()


@contextlib.contextmanager
def use_trace(trace_obj: Optional[_TraceSink]) -> Iterator[None]:
    token = _current.set(trace_obj)
    try:
        yield
    finally:
        _current.reset(token)


@contextlib.contextmanager
def span(name: str, **labels) -> Iterator[Optional[Span]]:
    """Record a span on the ambient trace; no-op (still yields) when no
    trace is active — runtime modules call this unconditionally."""
    tr = _current.get()
    if tr is None:
        yield None
        return
    with tr.span(name, **labels) as s:
        yield s


def record(name: str, t0: float, t1: float, **labels) -> None:
    """Attach an already-timed span to the ambient trace (no-op without
    one) — for call sites that measured the window themselves."""
    tr = _current.get()
    if tr is not None:
        tr.add_span(name, t0, t1, **labels)


def annotate_span(**labels) -> None:
    """Merge labels into the innermost OPEN span of the ambient trace
    (no-op without one) — e.g. the prefix store marking hit depth on the
    enclosing prefill span."""
    tr = _current.get()
    if tr is None:
        return
    stack = tr._stack()
    if stack:
        stack[-1].labels.update(labels)


class FlightRecorder:
    """Bounded ring of the last N completed request traces, served at
    ``GET /debug/requests`` — the after-the-fact view of where a slow
    request's time went, no profiler attached."""

    def __init__(self, capacity: int = 64):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._lock = graftsched.lock("tracing.FlightRecorder._lock")
        self._traces: "deque[RequestTrace]" = deque(maxlen=capacity)

    def record(self, trace_obj: RequestTrace) -> None:
        trace_obj.finish()
        with self._lock:
            self._traces.append(trace_obj)

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def find(self, request_id: str) -> Optional[dict]:
        """Newest recorded trace with this X-Request-ID as a JSON
        timeline, or None — the join point the fleet router stitches
        replica span trees through (newest wins on rid reuse, same as
        the graftload TTFT join)."""
        with self._lock:
            traces = list(self._traces)
        for t in reversed(traces):
            if t.request_id == request_id:
                return t.to_dict()
        return None

    def snapshot(self, n: Optional[int] = None, slowest: bool = False,
                 errors_only: bool = False,
                 profile: Optional[str] = None) -> List[dict]:
        """Most recent (or slowest) ``n`` traces as JSON timelines,
        newest/slowest first. ``errors_only`` keeps only error-labeled
        traces (failed/shed/degraded requests) — the fault-triage view
        ``/debug/requests?errors=1`` serves. ``profile`` keeps only
        traces whose X-Workload-Profile label matches — the per-
        workload triage view a graftload run uses to isolate one
        traffic shape's slow/failed requests."""
        with self._lock:
            traces = list(self._traces)
        traces.reverse()                      # newest first
        if errors_only:
            traces = [t for t in traces if "error" in t.labels]
        if profile is not None:
            traces = [t for t in traces
                      if t.labels.get("profile") == profile]
        if slowest:
            traces.sort(key=lambda t: t.duration, reverse=True)
        if n is not None:
            traces = traces[:max(n, 0)]
        return [t.to_dict() for t in traces]


def debug_requests_payload(recorder: FlightRecorder, query: dict,
                           serving: dict):
    """The ``/debug/requests`` response body (?n/?slowest/?errors/
    ?profile) — ONE implementation shared by the replica surface
    (serving/app.py) and the fleet router (serving/router.py), so a
    new query filter cannot land on one debug surface and silently
    desynchronize the other. ``serving`` is the per-app identity
    block. Returns ``(422, detail)`` on an unparseable ``n``."""
    try:
        n = int(query.get("n", "32"))
    except ValueError:
        return 422, {"detail": "n must be an integer"}
    slowest = query.get("slowest", "").lower() in ("1", "true", "yes")
    errs = query.get("errors", "").lower() in ("1", "true", "yes")
    prof = query.get("profile") or None
    return {
        "serving": serving,
        "capacity": recorder.capacity,
        "recorded": len(recorder),
        "order": "slowest" if slowest else "newest",
        **({"profile": prof} if prof else {}),
        "requests": recorder.snapshot(n=n, slowest=slowest,
                                      errors_only=errs, profile=prof),
    }


# process-wide default recorder (what serving.app uses; injectable there)
RECORDER = FlightRecorder()
