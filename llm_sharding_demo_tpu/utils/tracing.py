"""Profiling/tracing helpers around ``jax.profiler``.

SURVEY.md §5 "Tracing / profiling": the reference imports ``time`` and
never uses it (reference server.py:3). Here:

- ``trace(dir)``: context manager capturing an XLA/TPU profile viewable
  in TensorBoard/Perfetto (device timelines, HLO cost, HBM traffic);
- ``annotate(name)``: named span that shows up inside those traces
  (``jax.profiler.TraceAnnotation``);
- ``timed(name)``: lightweight host-side wall-clock span recording into
  ``utils.metrics.REGISTRY`` — the per-request numbers /metrics exposes.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax

from .metrics import REGISTRY


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False) -> Iterator[None]:
    """Capture a device-level profiler trace into ``log_dir``."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span visible in profiler traces (device + host timelines)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def timed(name: str, registry=None, **labels) -> Iterator[None]:
    """Wall-clock span recorded as a histogram observation."""
    reg = registry if registry is not None else REGISTRY
    t0 = time.perf_counter()
    try:
        yield
    finally:
        reg.observe(name, time.perf_counter() - t0, **labels)
