"""grafttime: the unified causal timeline — one clock, every producer.

The spine emits rich telemetry in silos: ``RequestTrace`` span trees
(``/debug/requests``), graftscope dispatch rings and occupancy series
(``/debug/profile``), graftsched lock accounting, graftfault injections
and breaker transitions, iterbatch park/preempt/resume and pool
admission/eviction, graftwatch plan evaluations (``/debug/plan``), and
loadgen arrival schedules. None of them share a clock, so "what
happened during this p99 request" means hand-joining five JSON payloads
by X-Request-ID. This module is the dynamic half of the graftcheck
``timeline`` pass (``tools/graftcheck/timeline.py`` is the static half
— the same static+dynamic split as graftsan/graftlock/graftfault):

- **one bounded event bus** (:class:`TimelineBus`): every producer
  publishes typed events onto one monotonic clock (``perf_counter``
  relative to the bus epoch, the same clock family graftscope's
  ``t_ms`` uses). The ring is BOUNDED (oldest dropped, never unbounded
  growth) and lock-light: one plain-lock deque append per event. The
  bus's own lock is a plain ``threading.Lock`` — deliberately NOT a
  ``graftsched.lock`` — because graftsched's instrumented locks
  themselves publish ``lock_acquire`` events here, and the apparatus
  must not observe (or recurse into) itself;
- **a fixed event vocabulary** (:data:`EVENT_KINDS`): emission is a
  DECLARED contract — every producing module declares
  ``TIMELINE_EVENTS = {kind: source}`` and the timeline pass verifies
  every declared kind is emitted, every emitted kind is declared and
  in-vocabulary, and required correlator fields are present at each
  emit site;
- **correlators**: events join by ``rid`` (X-Request-ID — a shared
  batched dispatch carries ``rids``, the fanout-span analog),
  ``key`` (the certifier's program key, stringified), and ``replica``
  (the serving app's fleet label, ambient per request);
- **serving**: ``GET /debug/timeline`` (``?rid=``, ``?since=``,
  ``?kinds=``, ``?n=``) serves the raw stream; ``python -m
  tools.grafttime export`` converts a captured stream (or a black-box
  dump) to Chrome-trace/Perfetto JSON;
- **black-box dumps**: when a typed ``Unavailable`` or a
  ``GraftsanError`` surfaces at a serving boundary, the current ring is
  journaled (:func:`blackbox`) into a bounded in-process dump ring —
  and to ``$GRAFTTIME_DIR/grafttime_blackbox_*.json`` when that env var
  names a directory — so the events that LED to the failure survive it.

Clock model: all in-process producers (the fleet harness's replicas
included) share ONE bus and therefore one clock, so cross-replica
events are aligned by construction. Across real processes each side
has its own epoch; :func:`rebase` shifts a downstream replica's events
onto the caller's clock by the hop offset — exactly the trace-stitching
offset ``RequestTrace.graft`` uses (the skew is the hop's queueing,
which is precisely what the offset shows).

Replay contract (the FaultPlan/GRAFTSCHED discipline): a request's
event stream is replay-identical under a pinned seed MODULO the
declared wall-clock fields (:data:`REPLAY_EXEMPT_FIELDS`) and the
declared schedule-observation kinds (:data:`REPLAY_EXEMPT_KINDS` —
lock and occupancy events observe the interleaving itself and are
exempt by design). :func:`replay_view` is THE canonical projection the
determinism pins compare byte-for-byte.

Overhead: one enabled-flag check, one ``perf_counter`` read, and one
plain-lock deque append per event. The pinned bound
(tests/test_grafttime.py, the graftscope pattern): a quick-tier decode
run with the bus armed stays within :data:`OVERHEAD_FACTOR` of bus-off
wall time, min-of-3. ``GRAFTTIME=0`` disables recording entirely.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Lock-discipline contract (tools/graftcheck locks pass): the event
# ring, the sequence counter, and the black-box dump ring are written
# by every producer thread and read by /debug/timeline handlers; all
# live under the plain module/bus ``_lock`` (see the module docstring
# for why these locks are deliberately never graftsched-instrumented).
GUARDED_STATE = {"_events": "_lock", "_seq": "_lock",
                 "_DUMPS": "_DUMPS_LOCK", "_DUMP_SEQ": "_DUMPS_LOCK"}
LOCK_ORDER = ("_lock",)

# Fault contract (tools/graftcheck faults pass): the bus owns no
# blocking boundaries — emission is a bounded in-memory append and the
# black-box file write is best-effort fire-and-forget. Declared empty
# so a blocking call added here must declare its policy.
FAULT_POLICY = {}

# -- the declared vocabulary --------------------------------------------------

# kind -> one-line meaning. THE fixed vocabulary: the timeline pass
# (tools/graftcheck/timeline.py) rejects any emitted or declared kind
# outside it, so a new event class is a reviewed vocabulary change, not
# an ad-hoc string.
EVENT_KINDS = {
    "arrival":        "loadgen fired a scheduled request at the app",
    "span_open":      "a request-trace span opened (tracing)",
    "span_close":     "a request-trace span closed, duration attached",
    "dispatch_begin": "an instrumented jit entry point began dispatch",
    "dispatch_end":   "an instrumented dispatch closed (program key + "
                      "window)",
    "occupancy":      "a live-state gauge sample (graftscope series)",
    "lock_acquire":   "an instrumented lock was acquired (GRAFTSCHED)",
    "lock_contend":   "an instrumented lock acquisition waited >1ms",
    "fault_inject":   "a seeded fault plan fired at a production site",
    "breaker":        "a circuit/park-budget breaker state observation",
    "admission":      "a scheduler admitted a request (seed/join)",
    "eviction":       "the pool LRU-evicted a prefix entry's blocks",
    "park":           "a live row parked (preemption or fault recovery)",
    "preempt":        "pool pressure chose a victim row to park",
    "resume":         "a parked row resumed by recompute",
    "plan_eval":      "graftwatch evaluated the plan set at a wave "
                      "boundary",
    "plan_switch":    "graftwatch installed a different certified plan",
    "mem_alloc":      "a graftmem ledger holding grew (byte delta + "
                      "component total)",
    "mem_free":       "a graftmem ledger holding shrank or retired",
    "trend_alert":    "a declared grafttrend watch tripped (burn/"
                      "drift/level)",
    "tier_demote":    "grafttier spilled a cold prefix entry's blocks "
                      "to the host-RAM tier",
    "tier_promote":   "grafttier promoted a demoted entry's blocks "
                      "back into the device pool",
}

# kind -> keyword arguments an emit SITE must spell out (values may be
# None at runtime — the contract is that the call site MENTIONS the
# correlator/payload, statically reviewable by the timeline pass).
KIND_FIELDS = {
    "arrival":        ("rid",),
    "span_open":      ("name",),
    "span_close":     ("name",),
    "dispatch_begin": ("scope", "key"),
    "dispatch_end":   ("scope", "key"),
    "occupancy":      ("name", "value"),
    "lock_acquire":   ("name",),
    "lock_contend":   ("name", "wait_ms"),
    "fault_inject":   ("site", "fault"),
    "breaker":        ("state",),
    "admission":      ("rid",),
    "eviction":       ("blocks",),
    "park":           ("rid", "reason"),
    "preempt":        ("rid",),
    "resume":         ("rid",),
    "plan_eval":      ("to_plan",),
    "plan_switch":    ("to_plan",),
    "mem_alloc":      ("component", "bytes"),
    "mem_free":       ("component", "bytes"),
    "trend_alert":    ("watch", "severity"),
    # tier movements are REPLAY-PINNED (like eviction): under a pinned
    # schedule the same entries demote/promote at the same points —
    # only the dur_ms a promote carries is wall-clock (already exempt
    # via REPLAY_EXEMPT_FIELDS)
    "tier_demote":    ("blocks",),
    "tier_promote":   ("blocks",),
}

# Replay contract: fields that carry wall-clock/interleaving truth and
# are therefore EXEMPT from byte-identity under a pinned seed...
REPLAY_EXEMPT_FIELDS = ("seq", "ts", "tid", "dur_ms", "wait_ms")
# ...and kinds that OBSERVE the schedule itself (lock events record the
# interleaving; occupancy values depend on when the sampler ran
# relative to other threads; graftmem byte deltas record residency as
# the allocator threads happened to interleave) — exempt as whole
# events.
REPLAY_EXEMPT_KINDS = ("lock_acquire", "lock_contend", "occupancy",
                       "mem_alloc", "mem_free")

# The declared overhead bound tests/test_grafttime.py pins (the
# graftscope pattern): a decode run with the bus armed must finish
# within this factor of the same run with the bus off, min-of-3.
OVERHEAD_FACTOR = 2.0

# bounded ring: oldest events drop — a ring, never a log
RING_CAPACITY = 4096
# bounded black-box dump ring (each dump snapshots the event ring)
BLACKBOX_CAPACITY = 8

_enabled = [os.environ.get("GRAFTTIME", "1") != "0"]


def enabled() -> bool:
    return _enabled[0]


def set_enabled(value: bool) -> bool:
    """Toggle recording (returns the previous value). The overhead test
    uses this for its bus-off baseline; production leaves it on."""
    prev = _enabled[0]
    _enabled[0] = bool(value)
    return prev


# -- ambient correlation ------------------------------------------------------

# A shared batched dispatch serves MANY requests (the fanout-span
# analog): the scheduler sets the live rid set around the dispatch so
# every event emitted inside carries them.
_RIDS: "contextvars.ContextVar[Tuple[str, ...]]" = contextvars.ContextVar(
    "grafttime_rids", default=())
# the serving app's fleet label, set per request by the handler
_REPLICA: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "grafttime_replica", default=None)


@contextlib.contextmanager
def correlate(rids: Sequence[str]):
    """Attach this rid set to every event emitted in the block (the
    scheduler wraps shared dispatches; None entries are dropped)."""
    token = _RIDS.set(tuple(r for r in rids if r))
    try:
        yield
    finally:
        _RIDS.reset(token)


def current_rids() -> Tuple[str, ...]:
    return _RIDS.get()


@contextlib.contextmanager
def use_replica(name: Optional[str]):
    """Attach a replica label to every event emitted in the block (the
    serving handler's per-request scope)."""
    token = _REPLICA.set(name)
    try:
        yield
    finally:
        _REPLICA.reset(token)


def set_thread_replica(name: Optional[str]) -> None:
    """Pin the replica label for the CURRENT thread's whole lifetime —
    what a scheduler worker calls at loop start, because the serving
    handler's per-request ``use_replica`` contextvar never propagates
    to a thread started at construction time."""
    _REPLICA.set(name)


def _ambient_rid() -> Tuple[Optional[str], Optional[Tuple[str, ...]]]:
    """(rid, rids) from the ambient correlation: the explicit
    ``correlate`` set first, else the ambient request trace."""
    rids = _RIDS.get()
    if rids:
        return (rids[0], None) if len(rids) == 1 else (None, rids)
    # lazy import: tracing imports THIS module at top level
    from . import tracing
    tr = tracing.current_trace()
    rid = getattr(tr, "request_id", None)
    return (rid, None) if rid else (None, None)


# -- the bus ------------------------------------------------------------------


class TimelineBus:
    """The process-wide causal event stream: a bounded ring of typed
    events on one monotonic clock."""

    def __init__(self, capacity: int = RING_CAPACITY):
        # plain lock by design — see the module docstring (the bus must
        # not recurse into graftsched's lock_acquire events)
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._seq = 0
        self.capacity = capacity
        self.t0 = time.perf_counter()
        self.epoch_unix = time.time()

    # -- clock --

    def to_ms(self, perf_t: float) -> float:
        """A ``perf_counter`` instant on the bus clock (ms since the
        bus epoch — the same family as graftscope's ``t_ms``)."""
        return round((perf_t - self.t0) * 1e3, 3)

    def now_ms(self) -> float:
        return self.to_ms(time.perf_counter())

    # -- recording --

    def emit(self, kind: str, *, rid=None, key: Optional[str] = None,
             replica: Optional[str] = None, t: Optional[float] = None,
             **fields) -> None:
        """Publish one typed event. ``rid`` may be a string, a sequence
        of strings (a shared batched phase), or None — None resolves
        from the ambient correlation (``correlate`` set, else the
        ambient request trace). ``t`` backdates the event to an
        already-measured ``perf_counter`` instant (schedulers stamping
        a window they timed themselves)."""
        if not _enabled[0]:
            return
        rids = None
        if rid is None:
            rid, rids = _ambient_rid()
        elif not isinstance(rid, str):
            seq_rids = tuple(r for r in rid if r)
            rid, rids = ((seq_rids[0], None) if len(seq_rids) == 1
                         else (None, seq_rids or None))
        if replica is None:
            replica = _REPLICA.get()
        ts = self.to_ms(time.perf_counter() if t is None else t)
        ev = {"kind": kind, "ts": ts,
              "tid": threading.get_ident()}
        if rid is not None:
            ev["rid"] = rid
        if rids:
            ev["rids"] = list(rids)
        if key is not None:
            ev["key"] = key
        if replica is not None:
            ev["replica"] = replica
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)

    # -- reading --

    def events(self, rid: Optional[str] = None,
               since: Optional[float] = None,
               kinds: Optional[Iterable[str]] = None,
               n: Optional[int] = None,
               since_seq: Optional[int] = None) -> List[dict]:
        """Filtered copy of the stream in CLOCK order (ts, seq-broken
        ties), oldest first — producers may backdate an event to an
        already-measured instant (a scheduler stamping a window it
        timed itself), so append order alone is not the causal order;
        the one clock is. ``rid`` matches the event's ``rid`` or
        membership in its ``rids``; ``since`` is an exclusive ``ts``
        lower bound (ms on the bus clock); ``since_seq`` is an
        exclusive lower bound on the monotonic emission sequence — the
        incremental-poll cursor: pass the previous payload's
        ``cursor`` and only events emitted after it come back (a
        backdated event emitted late is still delivered, which the
        ts-based ``since`` would skip); ``kinds`` keeps only those
        kinds; ``n`` caps to the NEWEST n after filtering."""
        with self._lock:
            evs = list(self._events)
        # sort OUTSIDE the hold: every hot-path emit contends on this
        # lock, and an O(n log n) pass over a full ring inside the
        # critical section would stall producers on every debug poll
        evs.sort(key=lambda e: (e["ts"], e["seq"]))
        if rid is not None:
            evs = [e for e in evs
                   if e.get("rid") == rid or rid in e.get("rids", ())]
        if since is not None:
            evs = [e for e in evs if e["ts"] > since]
        if since_seq is not None:
            evs = [e for e in evs if e["seq"] > since_seq]
        if kinds is not None:
            keep = set(kinds)
            evs = [e for e in evs if e["kind"] in keep]
        if n is not None:
            n = int(n)
            evs = evs[-n:] if n > 0 else []   # n=0 means none, not all
        return [dict(e) for e in evs]

    def snapshot(self, rid: Optional[str] = None,
                 since: Optional[float] = None,
                 kinds: Optional[Iterable[str]] = None,
                 n: Optional[int] = None,
                 since_seq: Optional[int] = None) -> dict:
        """The ``/debug/timeline`` payload body: the filtered stream
        plus the clock header a consumer needs to join or rebase it.
        ``cursor`` echoes the newest emission sequence at snapshot
        time — feed it back as ``since_seq`` and the next poll returns
        only the increment (events whose seq rotated out of the ring
        between polls are honestly gone; ``dropped`` rising between
        polls is the gap detector)."""
        evs = self.events(rid=rid, since=since, kinds=kinds, n=n,
                          since_seq=since_seq)
        with self._lock:
            emitted = self._seq
            held = len(self._events)
        return {
            "enabled": enabled(),
            "capacity": self.capacity,
            "emitted_total": emitted,
            "cursor": emitted,
            "since_seq": since_seq,
            "dropped": max(emitted - held, 0),
            "clock": {
                "epoch_unix": round(self.epoch_unix, 6),
                "now_ms": self.now_ms(),
                "model": ("perf_counter ms since bus epoch; one shared "
                          "clock in-process, rebase() across processes"),
            },
            "kinds": dict(EVENT_KINDS),
            "events": evs,
        }

    # -- test isolation (tests/conftest.py) --

    def dump_state(self) -> tuple:
        with self._lock:
            return (list(self._events), self._seq, self.t0,
                    self.epoch_unix)

    def restore_state(self, state: tuple) -> None:
        events, seq, t0, epoch = state
        with self._lock:
            self._events = deque(events, maxlen=self.capacity)
            self._seq = seq
            self.t0 = t0
            self.epoch_unix = epoch

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._seq = 0
            self.t0 = time.perf_counter()
            self.epoch_unix = time.time()


def _env_capacity() -> int:
    try:
        n = int(os.environ.get("GRAFTTIME_CAP", ""))
    except ValueError:
        return RING_CAPACITY
    return n if n >= 1 else RING_CAPACITY


# process-wide default bus (what every producer publishes to; tests
# snapshot/restore it via the conftest fixture)
BUS = TimelineBus(_env_capacity())


# -- module-level conveniences (the call-site API) ---------------------------


def emit(kind: str, **kw) -> None:
    """The production hook — the form the timeline pass recognizes:
    ``grafttime.emit("<kind>", <required fields>, ...)`` with a literal
    kind from :data:`EVENT_KINDS`."""
    BUS.emit(kind, **kw)


def events(**kw) -> List[dict]:
    return BUS.events(**kw)


def snapshot(**kw) -> dict:
    return BUS.snapshot(**kw)


def to_ms(perf_t: float) -> float:
    return BUS.to_ms(perf_t)


def now_ms() -> float:
    return BUS.now_ms()


def dump_state() -> tuple:
    return BUS.dump_state()


def restore_state(state: tuple) -> None:
    BUS.restore_state(state)


def clear() -> None:
    BUS.clear()


# -- replay projection --------------------------------------------------------


def replay_view(evs: List[dict]) -> Dict[str, List[dict]]:
    """THE canonical determinism projection (module docstring "Replay
    contract"): per-rid substreams (shared ``rids`` events land in
    every member's substream), schedule-observation kinds dropped,
    wall-clock fields stripped. Two runs of the same seeded schedule
    must serialize this byte-identically (``json.dumps``, sorted
    rids); uncorrelated events are excluded — they belong to no
    request's causal story."""
    out: Dict[str, List[dict]] = {}
    for e in evs:
        if e["kind"] in REPLAY_EXEMPT_KINDS:
            continue
        targets = ([e["rid"]] if "rid" in e else list(e.get("rids", ())))
        if not targets:
            continue
        core = {k: v for k, v in e.items()
                if k not in REPLAY_EXEMPT_FIELDS}
        for r in targets:
            out.setdefault(r, []).append(core)
    return {r: out[r] for r in sorted(out)}


def rebase(evs: List[dict], offset_ms: float) -> List[dict]:
    """Shift a downstream process's events onto the caller's clock:
    ``ts += offset_ms`` where the offset is the hop start on the
    caller's clock (the ``RequestTrace.graft`` stitching rule — the
    skew IS the hop's queueing). In-process fleets share one bus and
    never need this; a wire deployment rebases each replica's fetched
    stream before merging."""
    out = []
    for e in evs:
        e2 = dict(e)
        e2["ts"] = round(e["ts"] + offset_ms, 3)
        out.append(e2)
    return out


# -- Chrome-trace / Perfetto export -------------------------------------------

# event phases (Chrome Trace Event Format): X = complete (ts + dur),
# i = instant, C = counter
_WINDOW_KINDS = {"span_close": "span", "dispatch_end": "dispatch"}


def _pid_of(replica: Optional[str], pids: Dict[str, int]) -> int:
    """Stable small pid per replica label (Chrome wants numeric pids);
    unlabeled events ride pid 1."""
    if not replica:
        return 1
    if replica not in pids:
        pids[replica] = 2 + len(pids)
    return pids[replica]


def export_chrome(evs: List[dict], meta: Optional[dict] = None) -> dict:
    """Convert a timeline stream to Chrome-trace JSON (load it in
    ``chrome://tracing`` or ui.perfetto.dev). Mapping: ``span_close`` /
    ``dispatch_end`` become complete ("X") slices over their measured
    window; ``occupancy`` becomes a counter ("C") series; everything
    else becomes an instant ("i") marker. Correlators ride ``args``;
    replicas map to pids, emitting threads to tids. ``ts`` is
    microseconds, per the format."""
    trace_events: List[dict] = []
    pids: Dict[str, int] = {}
    for e in evs:
        kind = e["kind"]
        args = {k: v for k, v in e.items()
                if k not in ("kind", "ts", "tid", "seq")}
        pid = _pid_of(e.get("replica"), pids)
        tid = int(e.get("tid", 0)) % 2 ** 31
        ts_us = max(e["ts"], 0.0) * 1e3
        if kind in _WINDOW_KINDS:
            dur_us = max(float(e.get("dur_ms", 0.0)), 0.0) * 1e3
            trace_events.append({
                "name": str(e.get("name") or e.get("scope") or kind),
                "cat": _WINDOW_KINDS[kind],
                "ph": "X",
                "ts": max(ts_us - dur_us, 0.0),
                "dur": dur_us,
                "pid": pid, "tid": tid, "args": args,
            })
        elif kind == "occupancy":
            trace_events.append({
                "name": str(e.get("name", "occupancy")),
                "cat": "occupancy",
                "ph": "C",
                "ts": ts_us,
                "pid": pid, "tid": tid,
                "args": {"value": float(e.get("value", 0.0))},
            })
        elif kind in ("mem_alloc", "mem_free"):
            # graftmem byte series: one Perfetto counter track per
            # component, plotting the component's running total (the
            # event's ``total`` field); the signed delta rides a
            # second counter key so the viewer can overlay causality
            trace_events.append({
                "name": f"hbm_bytes:{e.get('component', 'unknown')}",
                "cat": "graftmem",
                "ph": "C",
                "ts": ts_us,
                "pid": pid, "tid": tid,
                "args": {"value": float(e.get("total",
                                              e.get("bytes", 0))),
                         "delta": (float(e.get("bytes", 0))
                                   * (1 if kind == "mem_alloc"
                                      else -1))},
            })
        else:
            trace_events.append({
                "name": (f"{kind}:{e['name']}" if "name" in e
                         else (f"{kind}:{e['scope']}" if "scope" in e
                               else kind)),
                "cat": kind,
                "ph": "i",
                "s": "t",
                "ts": ts_us,
                "pid": pid, "tid": tid, "args": args,
            })
    return {
        "displayTimeUnit": "ms",
        "traceEvents": trace_events,
        "otherData": {"producer": "grafttime",
                      "kinds": sorted({e["kind"] for e in evs}),
                      **(meta or {})},
    }


_VALID_PH = {"X", "i", "C", "B", "E", "I"}


def validate_chrome(payload: dict) -> List[str]:
    """Structural schema check on an export (empty list = valid): the
    timeline pass runs this over a synthetic event per vocabulary kind,
    and the export tests run it over real streams, so a mapping bug
    fails statically before it fails a trace viewer."""
    problems: List[str] = []
    if not isinstance(payload, dict):
        return ["payload is not an object"]
    tes = payload.get("traceEvents")
    if not isinstance(tes, list):
        return ["traceEvents missing or not a list"]
    for i, te in enumerate(tes):
        where = f"traceEvents[{i}]"
        if not isinstance(te, dict):
            problems.append(f"{where}: not an object")
            continue
        if not isinstance(te.get("name"), str) or not te.get("name"):
            problems.append(f"{where}: name must be a non-empty string")
        if te.get("ph") not in _VALID_PH:
            problems.append(f"{where}: ph {te.get('ph')!r} invalid")
        if not isinstance(te.get("ts"), (int, float)) or te["ts"] < 0:
            problems.append(f"{where}: ts must be a number >= 0")
        for fld in ("pid", "tid"):
            if not isinstance(te.get(fld), int):
                problems.append(f"{where}: {fld} must be an int")
        if te.get("ph") == "X" and (
                not isinstance(te.get("dur"), (int, float))
                or te["dur"] < 0):
            problems.append(f"{where}: X event needs dur >= 0")
        if te.get("ph") == "i" and te.get("s") not in ("g", "p", "t"):
            problems.append(f"{where}: instant needs s in g/p/t")
    return problems


def sample_event(kind: str) -> dict:
    """A schema-complete synthetic event for one vocabulary kind — what
    the timeline pass feeds ``export_chrome``/``validate_chrome`` so
    export validity is checked per kind, compile-free."""
    if kind not in EVENT_KINDS:
        raise KeyError(f"unknown timeline kind {kind!r}")
    ev = {"kind": kind, "ts": 1.0, "tid": 1, "seq": 1, "rid": "r0",
          "replica": "solo"}
    fills = {"rid": "r0", "name": "x", "scope": "mod._fn", "key": "('k',)",
             "value": 1.0, "wait_ms": 0.1, "site": "mod.site",
             "fault": "kindname", "state": "closed", "blocks": 1,
             "reason": "preempt", "to_plan": "solo", "dur_ms": 0.5,
             "component": "params", "bytes": 1,
             "watch": "slo_ttft_burn", "severity": "page"}
    for f in KIND_FIELDS.get(kind, ()):
        ev[f] = fills[f]
    if kind in _WINDOW_KINDS:
        ev["dur_ms"] = 0.5
    return ev


# -- the /debug/timeline payload ----------------------------------------------


def debug_timeline_payload(query: dict, serving: dict):
    """The ``GET /debug/timeline`` response body (``?rid=``,
    ``?since=``, ``?since_seq=``, ``?kinds=``, ``?n=``) — ONE
    implementation shared by the replica surface (serving/app.py) and
    the fleet router (serving/router.py), the
    ``tracing.debug_requests_payload`` discipline: a new filter cannot
    land on one debug surface and silently desynchronize the other.
    ``serving`` is the per-app identity block. ``since_seq`` is the
    incremental-poll cursor: pass the previous payload's ``cursor``
    back and only newer emissions return. Returns ``(422, detail)`` on
    an unparseable or out-of-vocabulary filter."""
    since = query.get("since")
    if since is not None:
        try:
            since = float(since)
        except ValueError:
            return 422, {"detail": "since must be a number (ms on the "
                                   "bus clock)"}
    since_seq = query.get("since_seq")
    if since_seq is not None:
        try:
            since_seq = int(since_seq)
        except ValueError:
            return 422, {"detail": "since_seq must be an integer "
                                   "(the previous payload's cursor)"}
    kinds = None
    if query.get("kinds"):
        kinds = [k.strip() for k in query["kinds"].split(",")
                 if k.strip()]
        unknown = sorted(set(kinds) - set(EVENT_KINDS))
        if unknown:
            return 422, {"detail": f"unknown kinds {unknown}; "
                         f"vocabulary: {sorted(EVENT_KINDS)}"}
    n = query.get("n")
    if n is not None:
        try:
            n = int(n)
        except ValueError:
            return 422, {"detail": "n must be an integer"}
    return {
        "serving": serving,
        **BUS.snapshot(rid=query.get("rid") or None, since=since,
                       kinds=kinds, n=n, since_seq=since_seq),
    }


# -- black-box dumps ----------------------------------------------------------

_DUMPS_LOCK = threading.Lock()
_DUMPS: deque = deque(maxlen=BLACKBOX_CAPACITY)
_DUMP_SEQ = [0]   # monotonic file index (never reuses a name even
                  # after the bounded in-process ring rotates)


def blackbox(reason: str, rid: Optional[str] = None) -> dict:
    """Journal the current ring as a post-mortem dump: called by the
    serving layer when a typed ``Unavailable`` or a ``GraftsanError``
    surfaces, so the events that LED to the failure outlive the ring's
    rotation. Kept in a bounded in-process ring
    (:func:`blackbox_dumps`); additionally written to
    ``$GRAFTTIME_DIR/grafttime_blackbox_<n>_<reason>.json`` when that
    env var names a directory (best-effort — a failed write never
    masks the original failure)."""
    dump = {
        "reason": reason,
        "rid": rid,
        "t_wall": time.time(),
        **BUS.snapshot(),
    }
    with _DUMPS_LOCK:
        _DUMPS.append(dump)
        _DUMP_SEQ[0] += 1
        n = _DUMP_SEQ[0]   # monotonic: dump 9 must not clobber dump 8's
        # file just because the in-process ring holds only 8
    out_dir = os.environ.get("GRAFTTIME_DIR", "")
    if out_dir:
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in reason)[:48]
        path = os.path.join(out_dir, f"grafttime_blackbox_{n}_{safe}.json")
        try:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(dump, f, default=str)
        except OSError:
            pass  # post-mortem best-effort: never mask the failure
    return dump


def blackbox_dumps() -> List[dict]:
    with _DUMPS_LOCK:
        return list(_DUMPS)


def clear_blackbox() -> None:
    with _DUMPS_LOCK:
        _DUMPS.clear()
