"""Checkpoint save/restore (Orbax) — the subsystem the reference lacks.

The reference re-downloads full HF weights into every pod at import time
and never saves anything (reference server.py:40-42; SURVEY.md §5
"Checkpoint / resume": ABSENT). Here conversion is one explicit step
(``models.hf_convert`` or the ``tools/convert_hf.py`` CLI) and serving/
training restore from an Orbax checkpoint directory — so pods need no hub
access and each pipeline stage can load only its own parameter subset
(``load_stage_params``).

Layout on disk::

    <dir>/config.json          # GPT2Config fields (+ "family" tag)
    <dir>/params/              # Orbax PyTreeCheckpointer payload

In memory the block stack is ``[n_layer, ...]`` leaves (the ``lax.scan``
layout, models.gpt2.apply_blocks); on disk each layer is its own subtree
(``blocks/{i}/...``) so a pipeline-stage restore reads ONLY its layers'
bytes from storage (``load_stage_params`` — Orbax partial restore via
``transforms={}``). Round-1 review flagged the old stacked layout for
pulling the whole model through host RAM per stage pod; per-layer
storage is what makes the partial read possible at all, since Orbax
can skip whole arrays but not slice inside one. Pre-existing stacked
checkpoints still load (structural detection + full-read fallback).

Training state (params + optimizer + step counter) uses the same
mechanism under ``<dir>/train_state``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..models.gpt2 import GPT2Config, Params
from ..parallel import partition as P_

CONFIG_FILE = "config.json"
PARAMS_DIR = "params"
TRAIN_DIR = "train_state"


def _split_blocks(blocks: Params) -> dict:
    """Stacked ``[L, ...]`` block leaves -> ``{"0": layer_tree, ...}``."""
    n_layer = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    return {str(i): jax.tree.map(lambda x: np.asarray(x[i]), blocks)
            for i in range(n_layer)}


def _stack_blocks(per_layer: dict) -> Params:
    """``{"0": layer_tree, ...}`` -> stacked ``[L, ...]`` leaves.

    Copies layer by layer into preallocated output and drops each source
    layer as it lands, so peak host RAM is ~1x the stack plus the not-yet-
    copied layers — not the 2x of a naive ``np.stack`` over a list that
    keeps every source alive until the end.
    """
    keys = sorted(per_layer, key=int)
    n = len(keys)

    def _alloc(x):
        out = np.empty((n,) + np.shape(x), np.asarray(x).dtype)
        out[0] = x
        return out

    out = jax.tree.map(_alloc, per_layer[keys[0]])
    per_layer[keys[0]] = None
    for i, k in enumerate(keys[1:], start=1):
        jax.tree.map(lambda dst, src, i=i: dst.__setitem__(i, src),
                     out, per_layer[k])
        per_layer[k] = None  # free the source layer's arrays promptly
    return out


def _is_per_layer(blocks) -> bool:
    """Structural layout detection: per-layer checkpoints key blocks by
    layer index ("0", "1", ...); the legacy stacked layout keys them by
    module name ("attn", "ln_1", ...)."""
    return (isinstance(blocks, dict) and bool(blocks)
            and all(k.isdigit() for k in blocks))


def _config_family(config: GPT2Config) -> str:
    """Model-family tag written next to the config fields.

    ``dataclasses.asdict`` flattens every family to a plain dict; without a
    tag an MoE or llama checkpoint would restore as a GPT2Config crash
    (unknown fields) or — worse, if fields ever overlapped — as the wrong
    model.
    """
    from ..models.llama import LlamaConfig
    from ..models.moe import MoEConfig
    if isinstance(config, MoEConfig):
        return "moe"
    if isinstance(config, LlamaConfig):
        return "llama"
    return "gpt2"


def save(directory: str, params: Params, config: GPT2Config) -> None:
    """Write config + params (per-layer block layout — see module doc).
    Overwrites an existing checkpoint."""
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    payload = {"family": _config_family(config), **dataclasses.asdict(config)}
    with open(os.path.join(directory, CONFIG_FILE), "w") as f:
        json.dump(payload, f, indent=2)
    on_disk = {k: v for k, v in params.items() if k != "blocks"}
    on_disk["blocks"] = _split_blocks(params["blocks"])
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.join(directory, PARAMS_DIR), on_disk, force=True)


def load_config(directory: str) -> GPT2Config:
    with open(os.path.join(os.path.abspath(directory), CONFIG_FILE)) as f:
        fields = json.load(f)
    family = fields.pop("family", "gpt2")  # pre-tag checkpoints are dense
    if family == "moe":
        from ..models.moe import MoEConfig
        return MoEConfig(**fields)
    if family == "llama":
        from ..models.llama import LlamaConfig
        return LlamaConfig(**fields)
    if family != "gpt2":
        raise ValueError(f"unknown checkpoint model family {family!r}")
    return GPT2Config(**fields)


def load(directory: str) -> Tuple[GPT2Config, Params]:
    """Restore (config, params); restacks per-layer blocks into the
    in-memory ``[L, ...]`` scan layout. Legacy stacked checkpoints pass
    through unchanged."""
    directory = os.path.abspath(directory)
    config = load_config(directory)
    ckptr = ocp.PyTreeCheckpointer()
    params = ckptr.restore(os.path.join(directory, PARAMS_DIR))
    if _is_per_layer(params.get("blocks")):
        params = dict(params)
        params["blocks"] = _stack_blocks(params["blocks"])
    return config, params


def save_train_state(directory: str, params: Params, opt_state: Any,
                     step: int) -> None:
    """Mid-training snapshot: params + optimizer moments + step counter.

    A crashed/preempted training job resumes bit-exactly — Adam moments
    and the schedule position (optax's counter inside ``opt_state``) are
    part of the trajectory, so restarting from params alone would change
    every subsequent update. ``step`` is caller bookkeeping (data/loop
    position), saved alongside but not consulted by the optimizer. Lives
    under ``<dir>/train_state`` beside the serving layout.
    """
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    payload = {"params": params, "opt_state": opt_state,
               "step": jax.numpy.asarray(step)}
    ocp.PyTreeCheckpointer().save(
        os.path.join(directory, TRAIN_DIR), payload, force=True)


def load_train_state(directory: str, params_template: Params,
                     opt_state_template: Any) -> Tuple[Params, Any, int]:
    """Restore a ``save_train_state`` snapshot as ``(params, opt_state,
    step)``.

    Orbax serializes pytree STRUCTURE loosely (optax states are nested
    NamedTuples that round-trip as plain containers), so callers pass
    templates — typically a fresh ``TrainStep.init(...)`` result — and
    the restore maps leaves back onto the exact optimizer-state classes.
    ``restore_args`` built from the templates make leaves restore
    directly into the RESUMING job's shardings; without them orbax reads
    device layouts from the checkpoint file, which it itself flags as
    unsafe when the resumed pod's topology differs from the saver's —
    the exact preemption-resume case this function exists for.

    ``step`` is loop/data-position bookkeeping for the caller; the LR
    schedule's own position is optax state inside ``opt_state`` and
    restores with it regardless of this value.
    """
    directory = os.path.abspath(directory)
    template = {"params": params_template, "opt_state": opt_state_template,
                "step": jax.numpy.asarray(0)}
    restored = ocp.PyTreeCheckpointer().restore(
        os.path.join(directory, TRAIN_DIR), item=template,
        restore_args=ocp.checkpoint_utils.construct_restore_args(template))
    return restored["params"], restored["opt_state"], int(restored["step"])


def load_stage_params(directory: str, spec: P_.StageSpec,
                      ) -> Tuple[GPT2Config, Params]:
    """Restore only one pipeline stage's parameter subset — a TRUE partial
    read: Orbax fetches just the stage's layer subtrees (plus embeddings
    for the first stage / ln_f + the tied head table for the last), so
    neither device nor host memory ever holds the rest of the model. This
    is the storage-level fix for the reference quirk of every role holding
    the full model (server.py:108-110).

    Legacy stacked-layout checkpoints can't be read partially (one
    ``[L, ...]`` array per leaf on disk); those fall back to full restore
    + slice, as before.
    """
    directory = os.path.abspath(directory)
    path = os.path.join(directory, PARAMS_DIR)
    ckptr = ocp.PyTreeCheckpointer()
    disk_tree = ckptr.metadata(path).item_metadata.tree
    if not _is_per_layer(disk_tree.get("blocks")):
        config, params = load(directory)
        return config, P_.extract_stage_params(params, spec)
    config = load_config(directory)

    # Family detected structurally, mirroring extract_stage_params: the
    # llama tree carries an untied ``lm_head`` (and no ``wpe``); the
    # GPT-2/MoE tree ties its head to ``wte``.
    llama_tree = "lm_head" in disk_tree
    item: dict = {"blocks": {str(i): disk_tree["blocks"][str(i)]
                             for i in range(spec.start, spec.end)}}
    if spec.is_first:
        item["wte"] = disk_tree["wte"]
        if not llama_tree:
            item["wpe"] = disk_tree["wpe"]
    if spec.is_last:
        item["ln_f"] = disk_tree["ln_f"]
        if llama_tree:
            item["lm_head"] = disk_tree["lm_head"]
        else:
            item.setdefault("wte", disk_tree["wte"])  # tied LM head table
    # metadata leaves are placeholders; restore_type=np.ndarray reads each
    # array as host numpy (shape/dtype from disk) without consulting the
    # saver's sharding file — a stage pod's topology never matches the
    # saver's anyway. transforms={} limits the read to exactly the keys
    # present in ``item``.
    restore_args = jax.tree.map(
        lambda _: ocp.RestoreArgs(restore_type=np.ndarray), item)
    got = ckptr.restore(path, item=item, transforms={},
                        restore_args=restore_args)
    out: Params = {"blocks": _stack_blocks(got["blocks"])}
    if spec.is_first:
        out["wte"] = got["wte"]
        if not llama_tree:
            out["wpe"] = got["wpe"]
    if spec.is_last:
        out["ln_f"] = got["ln_f"]
        if llama_tree:
            out["lm_head"] = got["lm_head"]
        else:
            out["wte_out"] = got["wte"]
    return config, out
