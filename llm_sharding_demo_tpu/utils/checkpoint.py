"""Checkpoint save/restore (Orbax) — the subsystem the reference lacks.

The reference re-downloads full HF weights into every pod at import time
and never saves anything (reference server.py:40-42; SURVEY.md §5
"Checkpoint / resume": ABSENT). Here conversion is one explicit step
(``models.hf_convert`` or the ``tools/convert_hf.py`` CLI) and serving/
training restore from an Orbax checkpoint directory — so pods need no hub
access and each pipeline stage can load only its own parameter subset
(``load_stage_params``).

Layout on disk::

    <dir>/config.json          # GPT2Config fields
    <dir>/params/              # Orbax PyTreeCheckpointer payload

Training state (params + optimizer + step counter) uses the same
mechanism under ``<dir>/train_state``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from ..models.gpt2 import GPT2Config, Params
from ..parallel import partition as P_

CONFIG_FILE = "config.json"
PARAMS_DIR = "params"
TRAIN_DIR = "train_state"


def _config_family(config: GPT2Config) -> str:
    """Model-family tag written next to the config fields.

    ``dataclasses.asdict`` flattens both families to plain dicts; without a
    tag an MoE checkpoint would restore as a GPT2Config crash (unknown
    fields) or — worse, if fields ever overlapped — as the wrong model.
    """
    from ..models.moe import MoEConfig
    return "moe" if isinstance(config, MoEConfig) else "gpt2"


def save(directory: str, params: Params, config: GPT2Config) -> None:
    """Write config + params. Overwrites an existing checkpoint."""
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    payload = {"family": _config_family(config), **dataclasses.asdict(config)}
    with open(os.path.join(directory, CONFIG_FILE), "w") as f:
        json.dump(payload, f, indent=2)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.join(directory, PARAMS_DIR), params, force=True)


def load_config(directory: str) -> GPT2Config:
    with open(os.path.join(os.path.abspath(directory), CONFIG_FILE)) as f:
        fields = json.load(f)
    family = fields.pop("family", "gpt2")  # pre-tag checkpoints are dense
    if family == "moe":
        from ..models.moe import MoEConfig
        return MoEConfig(**fields)
    if family != "gpt2":
        raise ValueError(f"unknown checkpoint model family {family!r}")
    return GPT2Config(**fields)


def load(directory: str) -> Tuple[GPT2Config, Params]:
    """Restore (config, params) from ``save``'s layout."""
    directory = os.path.abspath(directory)
    config = load_config(directory)
    ckptr = ocp.PyTreeCheckpointer()
    params = ckptr.restore(os.path.join(directory, PARAMS_DIR))
    return config, params


def save_train_state(directory: str, params: Params, opt_state: Any,
                     step: int) -> None:
    """Mid-training snapshot: params + optimizer moments + step counter.

    A crashed/preempted training job resumes bit-exactly — Adam moments
    and the schedule position (optax's counter inside ``opt_state``) are
    part of the trajectory, so restarting from params alone would change
    every subsequent update. ``step`` is caller bookkeeping (data/loop
    position), saved alongside but not consulted by the optimizer. Lives
    under ``<dir>/train_state`` beside the serving layout.
    """
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    payload = {"params": params, "opt_state": opt_state,
               "step": jax.numpy.asarray(step)}
    ocp.PyTreeCheckpointer().save(
        os.path.join(directory, TRAIN_DIR), payload, force=True)


def load_train_state(directory: str, params_template: Params,
                     opt_state_template: Any) -> Tuple[Params, Any, int]:
    """Restore a ``save_train_state`` snapshot as ``(params, opt_state,
    step)``.

    Orbax serializes pytree STRUCTURE loosely (optax states are nested
    NamedTuples that round-trip as plain containers), so callers pass
    templates — typically a fresh ``TrainStep.init(...)`` result — and
    the restore maps leaves back onto the exact optimizer-state classes.
    ``restore_args`` built from the templates make leaves restore
    directly into the RESUMING job's shardings; without them orbax reads
    device layouts from the checkpoint file, which it itself flags as
    unsafe when the resumed pod's topology differs from the saver's —
    the exact preemption-resume case this function exists for.

    ``step`` is loop/data-position bookkeeping for the caller; the LR
    schedule's own position is optax state inside ``opt_state`` and
    restores with it regardless of this value.
    """
    directory = os.path.abspath(directory)
    template = {"params": params_template, "opt_state": opt_state_template,
                "step": jax.numpy.asarray(0)}
    restored = ocp.PyTreeCheckpointer().restore(
        os.path.join(directory, TRAIN_DIR), item=template,
        restore_args=ocp.checkpoint_utils.construct_restore_args(template))
    return restored["params"], restored["opt_state"], int(restored["step"])


def load_stage_params(directory: str, spec: P_.StageSpec,
                      ) -> Tuple[GPT2Config, Params]:
    """Restore only one pipeline stage's parameter subset.

    Fixes the reference quirk of every role holding the full model
    (server.py:108-110): a stage server restores the full tree then slices
    immediately, so only the stage subset stays referenced; device memory
    never sees the rest (host RAM does transiently — true partial-restore
    via Orbax transforms is a later optimization).
    """
    config, params = load(directory)
    return config, P_.extract_stage_params(params, spec)
