"""Bounded default-backend probe, in a subprocess.

With the TPU tunnel down, in-process ``jax.devices()`` can block forever
(round-4 failure: MULTICHIP_r04.json rc=124 — the parent hung at backend
init and the driver's timeout voided the artifact). Probing in a child
process under a hard timeout turns "hang" into a reportable state.
Shared by ``bench.py``'s pre-flight check and ``__graft_entry__``'s
mega-mosaic smoke gate so tunnel-behavior fixes land once.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional, Tuple

_PROBE_CODE = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"

# Fault contract (tools/graftcheck faults pass): the probe child runs
# under a configured hard timeout with capped linear-backoff retries;
# persistent failure degrades to skip-with-reason, never a hang.
FAULT_POLICY = {
    "subprocess.run": ("config", "capped-linear-backoff",
                       "skip-with-reason when the probe stays down"),
}


def probe_default_backend(timeout_s: float, attempts: int = 1,
                          backoff_s: float = 0.0,
                          env: Optional[dict] = None,
                          ) -> Tuple[Optional[str], Optional[str]]:
    """(platform, None) if the default backend answers within
    ``timeout_s``, else (None, reason).  ``attempts``/``backoff_s`` add
    linear-backoff retries for flaky-tunnel windows (sleep grows
    ``backoff_s * attempt`` between tries)."""
    env = dict(os.environ if env is None else env)
    reason = "unknown"
    for attempt in range(attempts):
        if attempt:
            time.sleep(backoff_s * attempt)
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                               capture_output=True, text=True, env=env,
                               timeout=timeout_s)
        except subprocess.TimeoutExpired:
            reason = (f"backend probe hung >{timeout_s:g}s "
                      f"(attempt {attempt + 1}/{attempts}; TPU tunnel down)")
            continue
        out = r.stdout or ""
        if r.returncode == 0 and "PLATFORM=" in out:
            return out.rsplit("PLATFORM=", 1)[1].split()[0], None
        reason = (f"backend probe rc={r.returncode} "
                  f"(attempt {attempt + 1}/{attempts}): "
                  + (r.stderr or "").strip()[-200:])
    return None, reason
