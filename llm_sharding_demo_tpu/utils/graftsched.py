"""graftsched: the lock-discipline race harness runtime (``GRAFTSCHED=1``).

The dynamic half of the graftcheck locks pass (``tools/graftcheck/
locks.py`` is the static half — same split as graftsan's sanitize pass
vs the ``GRAFTSAN=1`` pool sanitizer). The serving/runtime layer is
genuinely concurrent: ``ThreadingHTTPServer`` handler threads feed
background scheduler threads over shared allocator/prefix-store/
metrics/tracing state, and every declared lock in those modules is
constructed through :func:`lock`/:func:`rlock` here. With GRAFTSCHED
unset that is a zero-cost passthrough to ``threading.Lock``/``RLock``;
armed, every declared lock becomes a :class:`TracedLock` that

- records **runtime lock-order pairs** (lock B acquired while holding
  A) and reports an inversion the moment the opposite order is
  observed, with both call sites;
- detects **deadlock by acquisition timeout** (with wait-for cycle
  reporting across the held/waiting maps);
- accounts **contention** (total wait seconds / acquisitions /
  contended acquisitions per lock name — the ``concurrent_load`` bench
  row journals these);
- yields at acquire/release boundaries, either with **seeded jitter**
  (``GRAFTSCHED=1`` + ``GRAFTSCHED_SEED``: replayable schedule
  perturbation for the threaded integration tests) or under a
  **step-mode :class:`Harness`** that serializes registered threads and
  picks the next runnable one with a seeded RNG — the deterministic
  driver the seeded-race fixtures replay (same seed, same interleaving,
  same single finding).

Race traps the fixtures pin (each yields exactly ONE finding with
file:line + the schedule seed):

- :class:`Cell` — an instrumented guarded-state stand-in whose
  read-modify-write traps **lost updates** (a write justified by a read
  another thread's write has since invalidated);
- :func:`trace_admission` — wraps a real ``BlockAllocator`` so a grant
  justified by an earlier ``can_admit`` that leaves live blocks above
  the watermark is reported as an **atomic-check-act overshoot** (the
  429-admission shape ``BlockAllocator.admit_alloc`` closes — the
  atomic path is wrapped too and pinned to never overshoot);
- :class:`TracedLock` timeouts — **lock-order inversion deadlock**.

This module is the measurement apparatus and is deliberately excluded
from the static pass's own scan (the same way asan does not sanitize
its runtime): its internal state is guarded by the private ``_STATE``
lock, which is never traced.

Env knobs: ``GRAFTSCHED`` ("" / ``0`` off; ``1`` seeded-jitter
scheduling; ``trace`` accounting only, no yields), ``GRAFTSCHED_SEED``
(int, default 0). ``tests/conftest.py`` asserts no instrumented lock is
still held after every test.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import sys
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Cell", "DeadlockError", "Harness", "SchedFinding", "TracedLock",
    "clear", "contention", "enabled", "findings", "held_locks", "lock",
    "mode", "rlock", "seed", "trace_admission",
]

# Timeline contract (tools/graftcheck timeline pass): with GRAFTSCHED
# armed (sched/trace), every instrumented acquisition publishes onto
# the unified causal stream (utils/grafttime) — and contended ones
# (>1ms wait) separately — so lock waits sit on the same clock as the
# dispatches and spans they delay. Both kinds are schedule
# OBSERVATIONS and therefore replay-exempt (grafttime
# REPLAY_EXEMPT_KINDS). grafttime's own lock is a plain
# threading.Lock precisely so this emission cannot recurse.
TIMELINE_EVENTS = {
    "lock_acquire": "TracedLock.acquire",
    "lock_contend": "TracedLock.acquire",
}


def mode() -> str:
    """"" (off) | "sched" (seeded jitter yields) | "trace" (accounting
    only). Read at every ``lock()`` construction, so a test can arm the
    harness with ``monkeypatch.setenv`` before building the stack."""
    v = os.environ.get("GRAFTSCHED", "")
    if v in ("", "0"):
        return ""
    return "trace" if v == "trace" else "sched"


def enabled() -> bool:
    return mode() != ""


def seed() -> int:
    try:
        return int(os.environ.get("GRAFTSCHED_SEED", "0"))
    except ValueError:
        return 0


@dataclasses.dataclass(frozen=True)
class SchedFinding:
    """One dynamic finding — same coordinates as the static pass's
    ``core.Finding`` plus the schedule seed that reproduces it."""

    rule: str
    path: str
    line: int
    scope: str
    message: str
    seed: Optional[int] = None

    def format(self) -> str:
        tail = f" (seed={self.seed})" if self.seed is not None else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tail}"


class DeadlockError(RuntimeError):
    """An instrumented lock acquisition timed out (lock-order inversion
    deadlock detection). The finding carries the wait-for details."""


# internal bookkeeping lock — plain and NEVER traced (the apparatus must
# not schedule itself)
_STATE = threading.Lock()
_FINDINGS: List[SchedFinding] = []
_PAIRS: Dict[Tuple[str, str], str] = {}      # (outer, inner) -> site
_REPORTED: set = set()
_WAIT: Dict[str, List[float]] = {}           # name -> [wait_s, acqs, contended]
_WAITING: Dict[int, "TracedLock"] = {}       # tid -> lock being acquired
_LOCKS: "weakref.WeakSet[TracedLock]" = weakref.WeakSet()
_TLS = threading.local()
_ACTIVE: Optional["Harness"] = None          # ambient step/jitter harness
_RNG = random.Random(seed())                 # env-mode jitter RNG


def _call_site(skip_file: str = __file__) -> str:
    """``file.py:line (func)`` of the nearest frame outside this module
    — the provenance unit every finding carries (same helper shape as
    the graftsan sanitizer's)."""
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == skip_file:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return (f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno} "
            f"({f.f_code.co_name})")


def _site_parts(skip_file: str = __file__) -> Tuple[str, int, str]:
    f = sys._getframe(1)
    while f is not None and f.f_code.co_filename == skip_file:
        f = f.f_back
    if f is None:
        return "<unknown>", 0, "<unknown>"
    return (os.path.basename(f.f_code.co_filename), f.f_lineno,
            f.f_code.co_name)


def _emit(rule: str, message: str, *, seed_val: Optional[int] = None,
          site: Optional[Tuple[str, int, str]] = None) -> SchedFinding:
    path, line, scope = site if site is not None else _site_parts()
    f = SchedFinding(rule, path, line, scope, message, seed_val)
    h = _ACTIVE
    if h is not None:
        h.findings.append(f)
    else:
        with _STATE:
            _FINDINGS.append(f)
    return f


def findings() -> List[SchedFinding]:
    """Global (env-armed) findings; a step-mode Harness collects its own
    on ``harness.findings`` instead."""
    with _STATE:
        return list(_FINDINGS)


def clear() -> None:
    """Reset global findings + order pairs + contention accounting, and
    re-seed the env-mode jitter RNG from the current GRAFTSCHED_SEED
    (so an armed run that clears at its start replays its schedule)."""
    global _RNG
    with _STATE:
        _FINDINGS.clear()
        _PAIRS.clear()
        _REPORTED.clear()
        _WAIT.clear()
        _RNG = random.Random(seed())


def contention() -> Dict[str, dict]:
    """Per-lock-name contention totals from every traced acquisition:
    ``{name: {wait_seconds, acquisitions, contended}}`` — what the
    ``concurrent_load`` bench row journals."""
    with _STATE:
        return {name: {"wait_seconds": round(w[0], 6),
                       "acquisitions": int(w[1]),
                       "contended": int(w[2])}
                for name, w in sorted(_WAIT.items())}


def held_locks() -> List[str]:
    """Names of instrumented locks some thread still holds — the
    conftest leak check (a held lock after a test means a scheduler
    unwound without releasing)."""
    out = []
    for lk in list(_LOCKS):
        with _STATE:
            holders = sum(lk._owners.values())
        if holders:
            out.append(f"{lk.name} (held {holders}x)")
    return sorted(out)


def _held_stack() -> List["TracedLock"]:
    st = getattr(_TLS, "held", None)
    if st is None:
        st = _TLS.held = []
    return st


def _yield_point(tag: str) -> None:
    h = _ACTIVE
    if h is not None:
        h.point(tag)
        return
    if mode() == "sched":
        with _STATE:
            r = _RNG.random()
            d = _RNG.random()
        if r < 0.1:
            time.sleep(d * 5e-4)


class TracedLock:
    """Drop-in ``threading.Lock``/``RLock`` that records order pairs,
    detects deadlock by timeout, accounts contention, and yields to the
    ambient schedule at acquire/release."""

    def __init__(self, name: str, reentrant: bool = False,
                 timeout: float = 15.0,
                 seed_val: Optional[int] = None):
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self.name = name
        self.reentrant = reentrant
        self._timeout = timeout
        self._seed = seed() if seed_val is None else seed_val
        self._owners: Dict[int, int] = {}    # tid -> recursion depth
        _LOCKS.add(self)

    # -- order pairs ---------------------------------------------------------

    def _note_pair(self, outer: "TracedLock", site: str) -> None:
        if outer.name == self.name and outer is not self:
            return  # same-name different-instance nesting: not an order
        pair = (outer.name, self.name)
        rev = (self.name, outer.name)
        with _STATE:
            if pair not in _PAIRS:
                _PAIRS[pair] = site
            rev_site = _PAIRS.get(rev)
            key = frozenset(pair)
            if (rev_site is not None and pair != rev
                    and key not in _REPORTED):
                _REPORTED.add(key)
                report = True
            else:
                report = False
        if report:
            _emit("lock-order",
                  f"runtime lock-order inversion: {self.name!r} acquired "
                  f"while holding {outer.name!r} at {site}, but the "
                  f"opposite order was taken at {rev_site}",
                  seed_val=self._seed)

    # -- acquire/release -----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        tid = threading.get_ident()
        held = _held_stack()
        reenter = self.reentrant and self._owners.get(tid, 0) > 0
        site = _call_site()
        if not reenter:
            _yield_point(f"acquire:{self.name}")
            for h in held:
                if h is not self:
                    self._note_pair(h, site)
        budget = timeout if timeout != -1 else self._timeout
        t0 = time.perf_counter()
        if not blocking:
            ok = self._inner.acquire(False)
        else:
            ok = self._inner.acquire(True, 0.002)
            if not ok:
                # contended: free the step-mode token while we block so
                # the holder can be scheduled to release
                harness = _ACTIVE
                if harness is not None:
                    harness._block_begin()
                with _STATE:
                    _WAITING[tid] = self
                try:
                    ok = self._inner.acquire(True, budget)
                finally:
                    with _STATE:
                        _WAITING.pop(tid, None)
                    if harness is not None:
                        harness._block_end()
        wait = time.perf_counter() - t0
        with _STATE:
            w = _WAIT.setdefault(self.name, [0.0, 0, 0])
            w[0] += wait
            w[1] += 1
            if wait > 1e-3:
                w[2] += 1
        if ok:
            # lazy import: the bus must stay constructible before this
            # module finishes bootstrapping (and never instruments it)
            from . import grafttime
            wait_ms = round(wait * 1e3, 3)
            grafttime.emit("lock_acquire", name=self.name,
                           wait_ms=wait_ms)
            if wait > 1e-3:
                grafttime.emit("lock_contend", name=self.name,
                               wait_ms=wait_ms)
        if not ok and blocking:
            self._report_deadlock(budget, site)
            raise DeadlockError(
                f"acquisition of {self.name!r} timed out after "
                f"{budget:.2f}s (see the lock-order finding)")
        if ok:
            with _STATE:
                self._owners[tid] = self._owners.get(tid, 0) + 1
            held.append(self)
        return ok

    def _report_deadlock(self, budget: float, site: str) -> None:
        with _STATE:
            holders = {t: d for t, d in self._owners.items() if d}
            # wait-for walk: who holds me -> what are THEY waiting on
            cycle = [self.name]
            cur = self
            for _ in range(8):
                owner = next((t for t, d in cur._owners.items() if d),
                             None)
                if owner is None:
                    break
                nxt = _WAITING.get(owner)
                if nxt is None:
                    break
                cycle.append(nxt.name)
                if nxt is self:
                    break
                cur = nxt
            key = ("deadlock", frozenset(cycle))
            if key in _REPORTED:
                return
            _REPORTED.add(key)
        held_names = [h.name for h in _held_stack()]
        _emit("lock-order",
              f"deadlock (acquisition timeout {budget:.2f}s): waiting "
              f"for {self.name!r} while holding {held_names}; wait-for "
              f"chain {' -> '.join(cycle)}; holders: "
              f"{len(holders)} thread(s)",
              seed_val=self._seed)

    def release(self) -> None:
        tid = threading.get_ident()
        with _STATE:
            d = self._owners.get(tid, 0)
            if d <= 1:
                self._owners.pop(tid, None)
            else:
                self._owners[tid] = d - 1
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._inner.release()
        _yield_point(f"release:{self.name}")

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def lock(name: str, timeout: float = 15.0):
    """A declared lock: plain ``threading.Lock`` when GRAFTSCHED is off
    (zero overhead on the production path), a :class:`TracedLock`
    otherwise. ``name`` is the reporting/contention key — use the
    ``module.Class.attr`` form the declarations reference."""
    if not enabled():
        return threading.Lock()
    return TracedLock(name, reentrant=False, timeout=timeout)


def rlock(name: str, timeout: float = 15.0):
    """Reentrant form of :func:`lock`."""
    if not enabled():
        return threading.RLock()
    return TracedLock(name, reentrant=True, timeout=timeout)


# -- step-mode harness --------------------------------------------------------


class Harness:
    """Seeded cooperative scheduler for 2-4 real threads.

    ``step=True`` serializes registered threads: exactly one runs at a
    time, and at every yield point the next runnable thread is picked
    with the seeded RNG — the same seed replays the same interleaving
    (threads are identified by registration order, never by OS ids).
    ``step=False`` is the jitter mode the integration tests use: seeded
    sleeps at yield points perturb the schedule replayably.

    Findings raised by traps while the harness is ambient land on
    ``self.findings`` (not the process-global list), so fixture runs
    cannot pollute the suite-level accounting.
    """

    def __init__(self, seed: int = 0, step: bool = True,
                 jitter: float = 0.1, lock_timeout: float = 2.0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.step = step
        self.jitter = jitter
        self.lock_timeout = lock_timeout
        self.findings: List[SchedFinding] = []
        self._cv = threading.Condition()
        self._state: Dict[int, str] = {}     # tid -> state
        self._index: Dict[int, int] = {}     # tid -> registration order
        self._current: Optional[int] = None
        self._abort = False
        self._errors: List[BaseException] = []

    def lock(self, name: str, reentrant: bool = False) -> TracedLock:
        return TracedLock(name, reentrant=reentrant,
                          timeout=self.lock_timeout, seed_val=self.seed)

    @contextlib.contextmanager
    def use(self):
        global _ACTIVE
        prev = _ACTIVE
        _ACTIVE = self
        try:
            yield self
        finally:
            _ACTIVE = prev

    # -- yield points --------------------------------------------------------

    def point(self, tag: str = "") -> None:
        tid = threading.get_ident()
        if not self.step:
            with self._cv:
                r = self.rng.random()
                d = self.rng.random()
            if r < self.jitter:
                time.sleep(d * 5e-4)
            return
        if tid not in self._state:
            return  # unregistered thread (e.g. the driving test)
        with self._cv:
            self._state[tid] = "parked"
            if self._current == tid:
                self._current = None
            self._cv.notify_all()
            while self._current != tid:
                if self._abort:
                    raise RuntimeError("graftsched harness aborted")
                self._cv.wait(0.02)
            self._state[tid] = "running"

    def _block_begin(self) -> None:
        tid = threading.get_ident()
        if not self.step or tid not in self._state:
            return
        with self._cv:
            self._state[tid] = "blocked"
            if self._current == tid:
                self._current = None
            self._cv.notify_all()

    def _block_end(self) -> None:
        tid = threading.get_ident()
        if not self.step or tid not in self._state:
            return
        self.point("unblocked")

    # -- driving -------------------------------------------------------------

    def _entry(self, i: int, fn: Callable[[], None]) -> None:
        # SELF-registration, before any user code: registering from
        # run() after start() would let a fast thread sail past its
        # first yield point unscheduled (the whole fixture would run
        # serially and the race never manifests)
        tid = threading.get_ident()
        with self._cv:
            self._state[tid] = "new"
            self._index[tid] = i
            self._cv.notify_all()
        try:
            self.point("start")
            fn()
        except DeadlockError:
            pass  # the finding IS the signal; the thread unwinds
        except BaseException as e:  # noqa: BLE001 — surfaced by run()
            with self._cv:
                self._errors.append(e)
        finally:
            with self._cv:
                self._state[tid] = "done"
                if self._current == tid:
                    self._current = None
                self._cv.notify_all()

    def run(self, fns: Sequence[Callable[[], None]],
            timeout: float = 30.0) -> None:
        """Drive ``fns`` (one thread each) to completion under the
        seeded schedule. Raises the first non-deadlock thread error;
        deadlocks surface as findings, not exceptions."""
        threads = []
        for i, fn in enumerate(fns):
            t = threading.Thread(target=self._entry, args=(i, fn),
                                 daemon=True,
                                 name=f"graftsched-{self.seed}-{i}")
            threads.append(t)
        for t in threads:
            t.start()
        deadline = time.monotonic() + timeout
        with self._cv:
            while len(self._state) < len(fns):
                self._cv.wait(0.02)
                if time.monotonic() > deadline:
                    raise TimeoutError("harness threads never registered")
            while any(s != "done" for s in self._state.values()):
                if time.monotonic() > deadline:
                    self._abort = True
                    self._cv.notify_all()
                    raise TimeoutError(
                        f"harness run exceeded {timeout}s: states "
                        f"{dict(self._state)}")
                if self.step and self._current is None:
                    runnable = sorted(
                        (tid for tid, s in self._state.items()
                         if s in ("parked", "new")),
                        key=lambda tid: self._index[tid])
                    if runnable:
                        self._current = self.rng.choice(runnable)
                        self._cv.notify_all()
                self._cv.wait(0.02)
        for t in threads:
            t.join(timeout=5.0)
        if self._errors:
            raise self._errors[0]


# -- race traps ---------------------------------------------------------------


class Cell:
    """Instrumented guarded-state stand-in: a read-modify-write slot
    whose ``set`` traps LOST UPDATES (the value being written was
    computed from a read another thread's write has since invalidated
    — the unguarded-gauge bug shape). Reads and writes are yield
    points, so the harness can interleave two incrementers exactly at
    the hazard."""

    def __init__(self, value=0, name: str = "cell"):
        self.name = name
        self._value = value
        self._version = 0
        self._tls = threading.local()

    def get(self):
        with _STATE:
            v, ver = self._value, self._version
        self._tls.read_version = ver
        _yield_point(f"{self.name}:read")
        return v

    def set(self, value) -> None:
        _yield_point(f"{self.name}:write")
        site = _site_parts()
        h = _ACTIVE
        with _STATE:
            read_ver = getattr(self._tls, "read_version", None)
            lost = read_ver is not None and read_ver != self._version
            self._version += 1
            self._value = value
        self._tls.read_version = None
        if lost:
            _emit("lost-update",
                  f"lost update on {self.name!r}: this write was "
                  "computed from a read another thread's write "
                  "invalidated — the intervening update is silently "
                  "overwritten (guard the read-modify-write with one "
                  "lock hold)",
                  seed_val=h.seed if h is not None else seed(),
                  site=site)

    @property
    def value(self):
        with _STATE:
            return self._value


def trace_admission(alloc) -> None:
    """Arm the check-then-act admission trap on a real
    ``BlockAllocator`` instance: a grant whose justification was an
    earlier ``can_admit`` — with live blocks past the watermark by the
    time the grant lands — is an ATOMIC-CHECK-ACT overshoot finding
    (the 429 admission shape ``admit_alloc`` exists to close). The
    atomic ``admit_alloc`` path is wrapped too and must never fire."""
    orig_can = alloc.can_admit
    orig_alloc = alloc.alloc
    orig_admit = alloc.admit_alloc
    checked = threading.local()

    def _limit() -> float:
        return alloc.watermark * alloc.num_blocks

    def _live() -> int:
        with alloc._lock:
            return len(alloc._ref) - alloc._evictable_blocks_locked()

    def can_admit(n: int) -> bool:
        ok = orig_can(n)
        if ok:
            checked.site = _site_parts()
        _yield_point("admission:checked")
        return ok

    def alloc_fn(n: int):
        out = orig_alloc(n)
        site = getattr(checked, "site", None)
        checked.site = None
        if site is not None and _live() > _limit():
            h = _ACTIVE
            _emit("atomic-check-act",
                  f"watermark admission overshoot: can_admit said yes, "
                  f"but by this grant live blocks exceed the watermark "
                  f"({_live()} > {_limit():g}) — the check and the "
                  "grant ran under separate lock holds "
                  "(BlockAllocator.admit_alloc is the atomic form)",
                  seed_val=h.seed if h is not None else seed(),
                  site=site)
        return out

    def admit_alloc(n: int):
        out = orig_admit(n)
        if out is not None and _live() > _limit():
            h = _ACTIVE
            _emit("atomic-check-act",
                  "admit_alloc overshot its own watermark — the atomic "
                  "admission path broke its contract",
                  seed_val=h.seed if h is not None else seed())
        _yield_point("admission:atomic")
        return out

    alloc.can_admit = can_admit
    alloc.alloc = alloc_fn
    alloc.admit_alloc = admit_alloc
