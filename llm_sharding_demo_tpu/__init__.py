"""TPU-native pipeline-sharded LLM inference/training framework.

A ground-up JAX/XLA rebuild of the capabilities of
``kanchan-rihan/llm-sharding-demo`` (reference: ``/root/reference/server.py``):
GPT-2 partitioned at transformer-block boundaries into pipeline stages, a
token-generation loop, and an HTTP ``/generate`` front end — redesigned
TPU-first:

- the model is a pure function over a parameter pytree (``models.gpt2``),
  blocks stacked on a leading layer axis so a single compiled ``lax.scan``
  covers all layers (instead of a Python loop of torch modules,
  reference server.py:84-85);
- stage-to-stage hidden-state handoff is an on-device ICI transfer
  (``parallel.pipeline``) instead of JSON-over-HTTP through a coordinator
  (reference server.py:172-181);
- decoding is a jitted on-device loop with a KV cache (``runtime.engine``)
  instead of an O(n^2) full re-forward per token (reference server.py:169);
- the FastAPI surface (``serving.app``) keeps the reference's routes and
  schemas (/generate, /forward, /forward_b — reference server.py:116-124)
  for wire-level compatibility.
"""

__version__ = "0.1.0"
