"""graftfleet router: prefix-affinity front end for a replica fleet.

The data-parallel generalization of the paper's coordinator (ROADMAP
item 2): instead of one coordinator driving two toy stage shards, a
router fronts N replicas split by PHASE — prefill replicas fill shared
pool blocks, decode replicas adopt them zero-copy through the
content-keyed prefix registry (``fleet/topology.py`` declares the
roles and what crosses each hop). Per request the router:

1. **routes by prefix-cache affinity**: the prompt's first-chunk
   content key — THE registry's own key, ``fleet/affinity.py`` — picks
   a decode replica off a consistent-hash ring, so requests sharing a
   cached prefix land where that prefix's blocks are warm. Keyless
   (short) prompts place by least load.
2. **warms the registry** through a prefill replica (``/prefill``)
   when one exists — a failed prefill hop DEGRADES (the decode replica
   prefills cold; correctness is unaffected, only the reuse win), it
   never fails the request.
3. **sheds per-replica**: a 429/503 from the chosen replica is typed
   backpressure, not death — the router falls over to the
   least-loaded other decode replica and only returns the shed
   (Retry-After intact) when every candidate refused. Transport
   failures ride a per-target ``HopPolicy`` circuit breaker
   (``hop_breaker_open{target=...}``), so a dead replica fails fast
   instead of stacking timeouts.
4. **honors X-Deadline-Ms end-to-end**: every hop's timeout derives
   from the remaining budget and the decremented budget is forwarded
   in-band, so the replica's own deadline machinery (queue-wait
   checks, segment-boundary cancellation) keeps enforcing it past the
   extra hop.
5. **stitches traces**: the replica's span tree (fetched from its
   flight recorder by the propagated X-Request-ID) is grafted under
   the router's hop span — ``/debug/requests`` here shows ONE tree
   per request, hop included.

Every cross-replica dispatch goes through ``FleetRouter._hop`` naming
a declared ``HANDOFF_POLICY`` entry, and the raw client call lives
only in the ``HOP_SCOPES`` function — both statically enforced by the
fleet pass (``tools/graftcheck/fleet.py``).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from ..fleet.affinity import HashRing, affinity_key
from ..fleet.topology import FleetTopology, ReplicaHandle
from ..utils import graftfault, graftsched, grafttime, graftwatch, \
    tracing
from ..utils.metrics import REGISTRY
from .app import GenerateReq, parse_deadline_header, parse_request_identity
from .http import JSONApp

log = logging.getLogger(__name__)

# Lock-discipline contract (tools/graftcheck locks pass): the router's
# cross-thread state is the per-replica in-flight counters and the
# affinity accounting — all leaf reads/bumps under ``_lock``; hops and
# sleeps run OUTSIDE any hold.
GUARDED_STATE = {"_inflight": "_lock", "affinity_hits": "_lock",
                 "affinity_fallbacks": "_lock", "sheds": "_lock"}
LOCK_ORDER = ("_lock",)

# Fault contract (tools/graftcheck faults pass): the router's one
# blocking boundary is the replica hop. Its per-attempt timeout derives
# from the request's remaining X-Deadline-Ms budget (also forwarded
# in-band so the replica keeps enforcing it); retries ride the typed
# per-target HopPolicy (capped backoff + breaker); failure degrades to
# least-loaded fallback and ultimately a typed 429/503 + Retry-After.
FAULT_POLICY = {
    "client.post": ("request", "hop-policy",
                    "per-target breaker, least-loaded fallback, typed "
                    "429/503 + Retry-After"),
}

# The ONLY scope allowed to speak the replica wire directly (fleet
# pass, undeclared-replica-hop rule): every other path dispatches
# through ``_hop``, which names a declared HANDOFF_POLICY entry.
HOP_SCOPES = ("FleetRouter._attempt",)


class _InjectedShed:
    """What a seeded ``http_503`` injection returns: the response shape
    of a real replica shed, so the drill drives the caller's typed
    shed/fallback path (Retry-After honored, breaker untouched) instead
    of the transport-retry path a real 503 never takes."""

    status_code = 503
    text = '{"error": "graftfault_injected_503"}'

    def __init__(self):
        self.headers = {"Retry-After": "1"}

    def json(self):
        return {"error": "graftfault_injected_503",
                "detail": "graftfault: injected replica 503"}


class ReplicaError(RuntimeError):
    """A replica hop failed at transport level (exception, or a 5xx
    that is not typed backpressure) — retried under the HopPolicy and
    counted against the target's breaker."""

    def __init__(self, target: str, detail: str):
        super().__init__(f"replica {target}: {detail}")
        self.target = target
        self.detail = detail


class FleetRouter:
    """Routing/shedding/stitching state for one fleet topology."""

    def __init__(self, topology: FleetTopology, tokenizer,
                 chunk: int = 64, registry=None, recorder=None,
                 hop_policy: Optional[graftfault.HopPolicy] = None):
        if chunk < 1:
            raise ValueError("chunk must be >= 1 (the prefix registry's "
                             "alignment width)")
        self.topology = topology
        self.tokenizer = tokenizer
        self.chunk = chunk
        self.registry = registry if registry is not None else REGISTRY
        self.recorder = (recorder if recorder is not None
                         else tracing.RECORDER)
        self.ring = HashRing([r.name for r in topology.decode_replicas])
        # warm traffic spreads across prefill replicas by the SAME
        # consistent-hash discipline as decode placement (a raw byte of
        # the content key would not do: affinity keys are little-endian
        # int32 token bytes, so fixed positions are structurally 0)
        self.prefill_ring = (
            HashRing([p.name for p in topology.prefill_replicas])
            if topology.prefill_replicas else None)
        # one policy, per-TARGET breakers (HopPolicy keys its breaker
        # table by the shard= label — here the replica name, which is
        # also the hop_breaker_open{target=...} series label)
        self.policy = hop_policy or graftfault.HopPolicy(
            attempts=2, timeout_s=30.0, base_backoff_s=0.05,
            max_backoff_s=0.5, breaker_threshold=4,
            breaker_cooldown_s=2.0,
            on_retry=lambda target, reason: self.registry.inc(
                "shard_hop_retries_total", stage=target, reason=reason))
        if self.policy.registry is None:
            # breaker gauges must land where this router's /metrics
            # reads — also for a caller-supplied policy, which would
            # otherwise fall back to the process-global REGISTRY
            self.policy.registry = self.registry
        self._lock = graftsched.lock("router.FleetRouter._lock")
        self._inflight: Dict[str, int] = {
            r.name: 0 for r in topology.replicas}
        self.affinity_hits = 0
        self.affinity_fallbacks = 0
        self.sheds = 0

    # -- load accounting ------------------------------------------------------

    def _note_start(self, name: str) -> None:
        with self._lock:
            self._inflight[name] += 1

    def _note_done(self, name: str) -> None:
        with self._lock:
            self._inflight[name] -= 1

    def inflight(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._inflight)

    def _note_affinity(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.affinity_hits += 1
            else:
                self.affinity_fallbacks += 1

    def _note_shed(self) -> None:
        with self._lock:
            self.sheds += 1

    def affinity_stats(self) -> dict:
        with self._lock:
            return {"hits": self.affinity_hits,
                    "fallbacks": self.affinity_fallbacks,
                    "sheds": self.sheds}

    # -- the hop --------------------------------------------------------------

    def _attempt(self, replica: ReplicaHandle, path: str, payload: dict,
                 headers: Dict[str, str], timeout_s: float):
        """THE wire touchpoint (HOP_SCOPES): one POST to one replica.
        In-process the client ignores ``timeout_s`` (dispatch is
        synchronous and the in-band X-Deadline-Ms budget is the real
        bound); a socket-backed client passes it to requests."""
        client = replica.client
        resp = client.post(path, json=payload, headers=headers,
                           timeout_s=timeout_s)
        if resp.status_code in (500, 502, 504):
            # an untyped replica failure is a transport-class fault:
            # retried, breaker-counted. Typed backpressure (429/503)
            # and request errors (4xx) return to the caller's logic.
            raise ReplicaError(replica.name,
                               f"HTTP {resp.status_code}: {resp.text[:120]}")
        return resp

    def _hop(self, hop: str, replica: ReplicaHandle, path: str,
             payload: dict, headers: Dict[str, str],
             deadline: Optional[graftfault.Deadline]):
        """One declared cross-replica dispatch (``hop`` names the
        HANDOFF_POLICY entry) through the per-target breaker. Seeded
        fault injection (site ``router.replica_hop``) lands before the
        wire call so the retry/fallback path replays deterministically.
        """
        fwd = dict(headers)

        def attempt(timeout_s: float):
            if deadline is not None:
                # the budget travels IN-BAND: the replica's own deadline
                # machinery enforces what remains after this hop's
                # queueing — recomputed PER ATTEMPT, so a retry after a
                # burned first attempt + backoff forwards the true
                # remainder, not the stale pre-hop budget
                fwd["X-Deadline-Ms"] = str(
                    max(1, int(deadline.remaining() * 1e3)))
            kind = graftfault.inject("router.replica_hop", "reset",
                                     "timeout", "http_503", "slow")
            if kind in ("reset", "timeout"):
                raise ReplicaError(replica.name,
                                   f"graftfault: injected hop {kind}")
            if kind == "http_503":
                # a replica answering 503 is TYPED backpressure, not a
                # transport fault: the drill must return it as a
                # response so the caller's shed/fallback accounting
                # replays exactly what a real 503 storm drives — not
                # retries and a breaker open a real 503 never causes
                return _InjectedShed()
            if kind == "slow":
                time.sleep(min(0.02, timeout_s))
            return self._attempt(replica, path, payload, fwd, timeout_s)

        return self.policy.call(attempt, shard=replica.name,
                                deadline=deadline)

    # -- placement ------------------------------------------------------------

    def decode_order(self, key: Optional[bytes]) -> List[ReplicaHandle]:
        """Candidate decode replicas, best first: the affinity-ring
        owner (when the prompt has a cacheable prefix), then the rest
        by ascending in-flight load (name-tiebroken so replays are
        deterministic)."""
        reps = self.topology.decode_replicas
        load = self.inflight()
        by_load = sorted(reps, key=lambda r: (load.get(r.name, 0), r.name))
        if key is None:
            return by_load
        primary = self.ring.pick(key)
        return ([r for r in reps if r.name == primary]
                + [r for r in by_load if r.name != primary])

    def prefill_order(self, key: Optional[bytes]
                      ) -> List[ReplicaHandle]:
        """Candidate prefill replicas, best first: the ring walk
        rotated to the content key's owner (deterministic warm spread
        across N replicas), REORDERED by the watcher's per-replica
        queue-depth estimate — the router's own in-flight counters,
        which are what it can observe of each replica's backlog. The
        sort is stable (graftwatch.order_by_queue_depth), so an idle
        fleet keeps exact ring placement while a backed-up prefill
        replica demotes past its peers instead of serializing every
        warm behind it (graftfleet follow-on b: fanout was
        first-replica-only in ring order)."""
        prefills = self.topology.prefill_replicas
        if not prefills or self.prefill_ring is None:
            return list(prefills)
        if key is None:
            names = [p.name for p in prefills]
        else:
            primary = self.prefill_ring.pick(key)
            start = next(i for i, p in enumerate(prefills)
                         if p.name == primary)
            names = [p.name for p in
                     prefills[start:] + prefills[:start]]
        load = self.inflight()
        ordered = graftwatch.order_by_queue_depth(names, load)
        by_name = {p.name: p for p in prefills}
        return [by_name[n] for n in ordered]


def create_router_app(topology: FleetTopology, tokenizer,
                      chunk: int = 64, registry=None, recorder=None,
                      hop_policy=None) -> JSONApp:
    """Build the router's serving surface. ``tokenizer`` must match the
    replicas' (affinity keys are token-content keys); ``chunk`` must
    match their prefix stores' alignment width — key drift between the
    router and the registry is exactly what the fleet pass exists to
    prevent."""
    router = FleetRouter(topology, tokenizer, chunk=chunk,
                         registry=registry, recorder=recorder,
                         hop_policy=hop_policy)
    reg = router.registry
    rec = router.recorder
    app = JSONApp(title="llm-sharding-demo-tpu-router", version="0.1.0")
    app.router = router  # harness/test introspection

    @app.get("/metrics")
    def metrics():
        return reg.prometheus()

    @app.get("/healthz")
    def healthz():
        return {
            "status": "ok",
            "role": "router",
            "replicas": topology.describe(),
            "chunk": router.chunk,
            "inflight": router.inflight(),
            "breakers": {r.name: router.policy.breaker_state(r.name)
                         for r in topology.replicas},
            "affinity": router.affinity_stats(),
        }

    @app.get("/debug/requests")
    def debug_requests(query: dict):
        """The router-side flight recorder: one JOINED tree per request
        (router spans + the replica's grafted subtree). Same filters as
        the replica view (?n/?slowest/?errors/?profile)."""
        return tracing.debug_requests_payload(
            rec, query, {"role": "router",
                         "replicas": topology.describe()})

    @app.get("/debug")
    def debug_index():
        """The router's debug-surface index (the replica app's /debug
        sibling): the surfaces this app serves, under its identity."""
        return {
            "serving": {"role": "router",
                        "replicas": topology.describe()},
            "surfaces": {
                "/debug/requests": (
                    "joined router+replica span trees per request "
                    "(?n, ?slowest=1, ?errors=1, ?profile=)"),
                "/debug/timeline": (
                    "grafttime unified causal event stream "
                    "(?rid=, ?since=, ?kinds=, ?n=)"),
            },
        }

    @app.get("/debug/timeline")
    def debug_timeline(query: dict):
        """The unified causal timeline at the router. Clock model: the
        in-process harness shares ONE bus (and therefore one clock)
        with every replica, so router and replica events are aligned
        by construction and ``clock_alignment`` reports offset 0. A
        wire deployment fetches each replica's /debug/timeline and
        rebases it by the hop start on the router's clock
        (``grafttime.rebase`` — the RequestTrace.graft stitching
        offset) before merging."""
        payload = grafttime.debug_timeline_payload(
            query, {"role": "router", "replicas": topology.describe()})
        if isinstance(payload, dict):
            payload["clock_alignment"] = {
                "mode": "shared-process-clock", "offset_ms": 0.0}
        return payload

    @app.post("/generate")
    def generate(req: GenerateReq, headers: dict):
        # the router's replica label on every event this request emits
        with grafttime.use_replica("router"):
            return _generate(req, headers)

    def _generate(req: GenerateReq, headers: dict):
        rid, profile_label = parse_request_identity(headers)
        fwd = {"X-Request-ID": rid}
        if profile_label is not None:
            fwd["X-Workload-Profile"] = profile_label
        hdrs = {"X-Request-ID": rid}

        def out(body, status=200):
            return status, body, hdrs

        deadline, _dl_ms, dl_err = parse_deadline_header(headers)
        if dl_err:
            return out({"error": dl_err}, status=400)

        trace = tracing.RequestTrace(rid, fleet="router", mode=req.mode)
        if profile_label is not None:
            trace.labels.update(profile=profile_label)

        with trace.span("tokenize"):
            prompt_ids = tokenizer.encode(req.prompt)
        if not prompt_ids:
            # reference-parity 200-with-error, but flight-recorded:
            # unrecorded rejects vanish from /debug/requests and
            # corrupt the router's accounting
            trace.labels.update(error="prompt tokenized to zero tokens")
            rec.record(trace)
            return out({"error": "prompt tokenized to zero tokens"})
        key = affinity_key(prompt_ids, router.chunk)
        body = req.model_dump()

        try:
            # -- prefill handoff (router->prefill): warm the registry.
            # Failure DEGRADES — the decode replica prefills cold. A
            # dead/unreachable replica falls over to the next prefill
            # replica (the registry is shared, so any of them can
            # warm); the walk starts at the prefill ring's owner and
            # is REORDERED by the watcher's per-replica queue-depth
            # estimate (router.prefill_order), so warm traffic spreads
            # deterministically across N idle replicas and routes
            # around a backed-up one. A typed shed does NOT fall over:
            # the pool is shared, so every prefill replica sees the
            # same saturation.
            prefills = topology.prefill_replicas
            if prefills and key is not None:
                warmed = False
                for p in router.prefill_order(key):
                    t0 = time.perf_counter()
                    try:
                        router._note_start(p.name)
                        try:
                            resp = router._hop("router->prefill", p,
                                               "/prefill",
                                               {"prompt": req.prompt},
                                               fwd, deadline)
                        finally:
                            router._note_done(p.name)
                    except graftfault.DeadlineExceeded:
                        raise
                    except (ReplicaError, graftfault.Unavailable) as e:
                        log.warning("prefill hop failed on %s: %s",
                                    p.name, e)
                        trace.add_span("prefill_hop", t0,
                                       time.perf_counter(),
                                       target=p.name,
                                       degraded=str(e)[:120])
                        continue
                    if resp.status_code != 200:
                        # a typed shed (429/503 kv_pool_saturated) or
                        # request error is NOT a warm — count it
                        # degraded so dashboards see the lost reuse
                        trace.add_span("prefill_hop", t0,
                                       time.perf_counter(),
                                       target=p.name,
                                       degraded=f"http_{resp.status_code}")
                        break
                    reg.inc("fleet_requests_total", target=p.name,
                            role="prefill")
                    _graft_replica(trace, "prefill_hop", p, rid,
                                   resp, t0, time.perf_counter())
                    warmed = True
                    break
                if not warmed:
                    # degraded, not failed: the decode replica
                    # prefills cold — correctness holds, only the
                    # reuse win is lost (and counted, once per
                    # request, so dashboards see it)
                    reg.inc("fleet_prefill_degraded_total")

            # -- decode handoff (router->decode): affinity target
            # first, least-loaded fallback on typed sheds or a dead
            # target's open breaker.
            order = router.decode_order(key)
            last_shed = None          # (status, body, Retry-After)
            last_unavailable = None
            resp = None
            target = None
            for i, r in enumerate(order):
                if deadline is not None:
                    deadline.raise_if_expired("route to decode replica")
                t0 = time.perf_counter()
                router._note_start(r.name)
                try:
                    resp = router._hop("router->decode", r, "/generate",
                                       body, fwd, deadline)
                except graftfault.DeadlineExceeded:
                    raise
                except (ReplicaError, graftfault.Unavailable) as e:
                    last_unavailable = e
                    trace.add_span("decode_hop", t0, time.perf_counter(),
                                   target=r.name, failed=str(e)[:120])
                    resp = None
                    continue
                finally:
                    router._note_done(r.name)
                if resp.status_code in (429, 503):
                    shed_body = resp.json()
                    if shed_body.get("error") == "deadline_exceeded":
                        # the request's OWN budget died on the replica
                        # — not backpressure: no other replica can save
                        # it, so falling over would just re-run a
                        # doomed request n_decode times. Surface it.
                        hdrs["Retry-After"] = (
                            resp.headers.get("Retry-After") or "1")
                        trace.add_span("decode_hop", t0,
                                       time.perf_counter(),
                                       target=r.name,
                                       deadline_exceeded=True)
                        trace.labels.update(error="deadline_exceeded")
                        rec.record(trace)
                        return out(shed_body, status=resp.status_code)
                    router._note_shed()
                    reg.inc("fleet_sheds_total", target=r.name,
                            code=str(resp.status_code))
                    last_shed = (resp.status_code, shed_body,
                                 resp.headers.get("Retry-After"))
                    trace.add_span("decode_hop", t0, time.perf_counter(),
                                   target=r.name,
                                   shed=resp.status_code)
                    resp = None
                    continue
                target = r
                reg.inc("fleet_requests_total", target=r.name,
                        role="decode")
                rep_tree = _graft_replica(trace, "decode_hop", r, rid,
                                          resp, t0, time.perf_counter())
                # a 4xx or the reference-parity 200-with-error body
                # completed the route but served no generation: keep it
                # out of the affinity accounting (bench's gated
                # affinity_hit_rate must measure routing quality, not
                # malformed-request volume) and label the trace
                err = (resp.json().get("error")
                       if resp.status_code != 200
                       or "error" in resp.json() else None)
                if err is not None:
                    trace.labels.update(error=str(err)[:120])
                else:
                    # lift the replica's summary labels onto the
                    # ROUTER trace: loadgen's trace join (and the
                    # fleet bench rows built on it) reads ttft_ms/
                    # new_tokens from the recorder it is handed —
                    # here, the router's. TTFT is re-based to the
                    # router clock (router time before the hop plus
                    # the replica's own first-token latency), which
                    # is what the client experienced.
                    rl = (rep_tree or {}).get("labels", {})
                    if "ttft_ms" in rl:
                        trace.labels.update(ttft_ms=round(
                            (t0 - trace.t0) * 1e3
                            + float(rl["ttft_ms"]), 3))
                    for lk in ("new_tokens", "prompt_tokens",
                               "finish_reason"):
                        if lk in rl:
                            trace.labels.setdefault(lk, rl[lk])
                    hit = key is not None and i == 0
                    router._note_affinity(hit)
                    if hit:
                        reg.inc("fleet_affinity_hits_total")
                    else:
                        reg.inc("fleet_affinity_fallbacks_total",
                                reason="no_key" if key is None
                                else "fallback")
                break

            if resp is None:
                # every decode replica refused: surface the TYPED shed
                # (Retry-After intact) — the fleet being saturated is
                # backpressure, not an opaque failure
                if last_shed is not None:
                    status, payload, retry = last_shed
                    hdrs["Retry-After"] = retry or "1"
                    trace.labels.update(error=payload.get(
                        "error", f"shed_{status}"))
                    rec.record(trace)
                    return out(payload, status=status)
                e = last_unavailable
                retry = getattr(e, "retry_after", 1.0)
                hdrs["Retry-After"] = str(max(1, int(round(retry))))
                trace.labels.update(error="fleet_unavailable")
                rec.record(trace)
                return out({"error": "fleet_unavailable",
                            "detail": str(e)}, status=503)
        except graftfault.Unavailable as e:
            hdrs["Retry-After"] = str(max(1, int(round(e.retry_after))))
            if e.code == "deadline_exceeded":
                reg.inc("deadline_misses_total")
            trace.labels.update(error=e.code)
            rec.record(trace)
            # post-mortem black box (grafttime): the fleet-level
            # failure with the causal stream that led to it
            grafttime.blackbox(e.code, rid=rid)
            return out({"error": e.code, "detail": str(e)}, status=503)

        trace.labels.update(target=target.name,
                            status=resp.status_code)
        trace.finish()
        rec.record(trace)
        payload = resp.json()
        # pass replica response headers the caller relies on through
        # (the echoed rid is the router's own)
        for h in ("Retry-After",):
            if h in resp.headers:
                hdrs[h] = resp.headers[h]
        return out(payload, status=resp.status_code)

    return app


def _graft_replica(trace: tracing.RequestTrace, name: str,
                   replica: ReplicaHandle, rid: str, resp,
                   t0: float, t1: float) -> Optional[dict]:
    """Stitch the replica's span tree under a hop span (in-process:
    the replica's flight recorder is on the handle; a wire deploy
    would fetch /debug/requests?n=1 by rid). Missing recorder or an
    evicted ring entry degrade to a bare hop span. Returns the
    replica's serialized trace so the caller can lift its summary
    labels (ttft_ms/new_tokens) onto the router trace."""
    payload = None
    if replica.recorder is not None:
        payload = replica.recorder.find(rid)
    trace.graft(name, payload, t0, t1, target=replica.name,
                status=resp.status_code)
    return payload
