"""Tokenizer resolution with an offline fallback.

The reference requires HF hub access in every pod for
``AutoTokenizer.from_pretrained`` at import (reference server.py:40). Here
the hub is optional: if the named tokenizer can't be loaded (air-gapped
TPU pod, no cache), a deterministic byte-level fallback keeps the
/generate surface functional — ids 0-255 are raw bytes. Model quality
through the fallback is meaningless for a GPT-2 checkpoint (different
vocab), but wire behavior, shapes, and tests don't depend on the hub.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Protocol

log = logging.getLogger(__name__)

# Subdirectory of a checkpoint dir where tools/convert_hf.py drops the HF
# tokenizer files (vocab.json/merges.txt/tokenizer.json...).
TOKENIZER_SUBDIR = "tokenizer"

# stdlib-re approximation of GPT-2's \p{L}/\p{N} split pattern, used when
# the `regex` module is absent (the transformers-free serving image).
# Letters via [^\W\d_]; punctuation must re-admit the underscore that \w
# claims. Module-level so tests can assert against THIS pattern, not a
# copy.
RE_FALLBACK_PATTERN = (r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+"
                       r"| ?(?:[^\w\s]|_)+|\s+(?!\S)|\s+")


class Tokenizer(Protocol):
    def encode(self, text: str) -> List[int]: ...
    def decode(self, ids: List[int]) -> str: ...


def _bytes_to_unicode() -> dict:
    """GPT-2's reversible byte -> printable-unicode-char table.

    BPE operates on strings; raw bytes that aren't printable latin-1 are
    remapped to 256+ codepoints so every byte has a distinct, visible
    symbol. (Same table as OpenAI's encoder.py / HF GPT2Tokenizer — it
    must be, or vocab.json symbols wouldn't line up.)
    """
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _get_pairs(word):
    return {(a, b) for a, b in zip(word, word[1:])}


class BPETokenizer:
    """Pure-Python GPT-2 byte-level BPE — zero dependencies.

    Serving pods deliberately exclude transformers/torch (Dockerfile,
    requirements.txt), so checkpoint-shipped tokenizer assets must be
    loadable without them; this class reads the standard ``vocab.json`` +
    ``merges.txt`` pair that ``save_pretrained`` writes. Without it, an
    air-gapped pod with perfectly converted weights would silently fall
    back to ``ByteTokenizer`` and generate garbage (byte ids are not BPE
    ids) — the round-1 advisor finding this class closes.

    The token-split regex needs ``\\p{L}``/``\\p{N}``; the stdlib ``re``
    can't express those, so when the ``regex`` module is absent we use the
    closest ``re`` translation (letters via ``[^\\W\\d_]``). The two agree
    on all ASCII and practically all natural text; exotic numerals (e.g.
    Roman-numeral codepoints) may split differently.
    """

    def __init__(self, vocab: dict, merges: List[tuple]):
        self.encoder = dict(vocab)
        self.decoder = {v: k for k, v in self.encoder.items()}
        self.ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.byte_enc = _bytes_to_unicode()
        self.byte_dec = {v: k for k, v in self.byte_enc.items()}
        self.cache: dict = {}
        try:
            import regex
            self.pat = regex.compile(
                r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+"
                r"| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")
        except ImportError:
            import re
            self.pat = re.compile(RE_FALLBACK_PATTERN)
        # unk fallback for pieces missing from vocab.json (mismatched
        # vocab/merges pair): degrade like HF's encoder.get(tok, unk)
        # instead of a serve-time KeyError on the first unlucky prompt
        self.unk_id = self.encoder.get("<|endoftext|>", 0)

    @classmethod
    def from_dir(cls, directory: str) -> "BPETokenizer":
        import json
        with open(os.path.join(directory, "vocab.json"),
                  encoding="utf-8") as f:
            vocab = json.load(f)
        merges = []
        with open(os.path.join(directory, "merges.txt"),
                  encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if not line or line.startswith("#version"):
                    continue
                a, _, b = line.partition(" ")
                merges.append((a, b))
        return cls(vocab, merges)

    def _bpe(self, token: str) -> List[str]:
        if token in self.cache:
            return self.cache[token]
        word = tuple(token)
        while len(word) > 1:
            pairs = _get_pairs(word)
            bigram = min(pairs, key=lambda p: self.ranks.get(p, float("inf")))
            if bigram not in self.ranks:
                break
            first, second = bigram
            new_word: List[str] = []
            i = 0
            while i < len(word):
                if (word[i] == first and i < len(word) - 1
                        and word[i + 1] == second):
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
        out = list(word)
        self.cache[token] = out
        return out

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for tok in self.pat.findall(text):
            sym = "".join(self.byte_enc[b] for b in tok.encode("utf-8"))
            ids.extend(self.encoder.get(piece, self.unk_id)
                       for piece in self._bpe(sym))
        return ids

    def decode(self, ids: List[int]) -> str:
        text = "".join(self.decoder.get(int(i), "") for i in ids)
        data = bytes(self.byte_dec[c] for c in text if c in self.byte_dec)
        return data.decode("utf-8", errors="replace")


class ByteTokenizer:
    """UTF-8 bytes <-> ids 0..255; unknown (>=256) ids decode as U+FFFD."""

    vocab_size = 256

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: List[int]) -> str:
        out = []
        run: List[int] = []  # decode contiguous byte runs together (UTF-8)
        for i in ids:
            if 0 <= i < 256:
                run.append(i)
            else:
                out.append(bytes(run).decode("utf-8", errors="replace"))
                out.append("�")  # visible marker for out-of-range ids
                run = []
        out.append(bytes(run).decode("utf-8", errors="replace"))
        return "".join(out)


def get_tokenizer(model_id: str,
                  checkpoint_dir: Optional[str] = None) -> Tokenizer:
    """Resolve a tokenizer: checkpoint assets -> HF cache/hub -> bytes.

    ``tools/convert_hf.py`` ships the tokenizer files inside the checkpoint
    directory (``<ckpt>/tokenizer``), so air-gapped pods restoring an Orbax
    checkpoint get the REAL BPE vocab — falling back to ``ByteTokenizer``
    with correctly converted weights would silently generate garbage (byte
    ids don't match GPT-2's vocab), hence the WARNING below.
    """
    if checkpoint_dir:
        tok_dir = os.path.join(checkpoint_dir, TOKENIZER_SUBDIR)
        if os.path.isdir(tok_dir):
            # Pure-Python loader first: identical behavior whether or not
            # transformers is installed (serving images exclude it).
            if os.path.exists(os.path.join(tok_dir, "vocab.json")):
                try:
                    return BPETokenizer.from_dir(tok_dir)
                except Exception as e:
                    log.warning("BPE load from %s failed (%s)", tok_dir, e)
            try:  # non-BPE formats (tokenizer.json-only checkpoints)
                from transformers import AutoTokenizer
                return AutoTokenizer.from_pretrained(
                    tok_dir, local_files_only=True)
            except Exception as e:
                log.warning("tokenizer assets at %s failed to load (%s); "
                            "trying HF id %s", tok_dir, e, model_id)
    try:
        from .loader import hub_reachable
        offline = not hub_reachable()  # before transformers import: sets
        from transformers import AutoTokenizer  # HF_HUB_OFFLINE in time
        return AutoTokenizer.from_pretrained(
            model_id, local_files_only=offline)
    except Exception as e:
        log.warning(
            "no tokenizer for %s (checkpoint assets absent, HF load failed: "
            "%s); using byte-level fallback — generations will NOT match the "
            "model's BPE vocab", model_id, e)
        return ByteTokenizer()
