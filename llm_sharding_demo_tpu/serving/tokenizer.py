"""Tokenizer resolution with an offline fallback.

The reference requires HF hub access in every pod for
``AutoTokenizer.from_pretrained`` at import (reference server.py:40). Here
the hub is optional: if the named tokenizer can't be loaded (air-gapped
TPU pod, no cache), a deterministic byte-level fallback keeps the
/generate surface functional — ids 0-255 are raw bytes. Model quality
through the fallback is meaningless for a GPT-2 checkpoint (different
vocab), but wire behavior, shapes, and tests don't depend on the hub.
"""

from __future__ import annotations

from typing import List, Protocol


class Tokenizer(Protocol):
    def encode(self, text: str) -> List[int]: ...
    def decode(self, ids: List[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes <-> ids 0..255; unknown (>=256) ids decode as U+FFFD."""

    vocab_size = 256

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: List[int]) -> str:
        out = []
        run: List[int] = []  # decode contiguous byte runs together (UTF-8)
        for i in ids:
            if 0 <= i < 256:
                run.append(i)
            else:
                out.append(bytes(run).decode("utf-8", errors="replace"))
                out.append("�")  # visible marker for out-of-range ids
                run = []
        out.append(bytes(run).decode("utf-8", errors="replace"))
        return "".join(out)


def get_tokenizer(model_id: str) -> Tokenizer:
    """HF tokenizer when loadable (cache/hub), else ``ByteTokenizer``."""
    try:
        from .loader import hub_reachable
        offline = not hub_reachable()  # before transformers import: sets
        from transformers import AutoTokenizer  # HF_HUB_OFFLINE in time
        return AutoTokenizer.from_pretrained(
            model_id, local_files_only=offline)
    except Exception:
        return ByteTokenizer()
