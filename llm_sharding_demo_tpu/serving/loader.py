"""Model resolution for serving: checkpoint -> HF cache/hub -> random init.

Replaces the reference's import-time ``AutoModelForCausalLM.from_pretrained``
in every pod (reference server.py:40-42) with an explicit resolution order:

1. ``CHECKPOINT_DIR`` set → Orbax restore (no hub, no torch, the
   production path);
2. the HF model is loadable (cached or hub reachable) → convert through
   ``models.hf_convert`` (torch imported only here, never on the TPU
   serving path);
3. otherwise → random init from the named architecture (keeps the service
   and its wire contract alive in air-gapped test environments; logged
   loudly since generations are untrained noise — which is also true of
   the reference's default tiny-gpt2, README.md:135).
"""

from __future__ import annotations

import logging
from typing import Tuple

import jax

from ..models import gpt2
from ..models.gpt2 import GPT2Config, Params
from ..utils import checkpoint as ckpt
from ..utils.config import ServingConfig

log = logging.getLogger(__name__)


def hub_reachable(timeout: float = 1.0) -> bool:
    """Fast offline detection: can we even resolve the HF hub host?

    Without this, air-gapped startups sit through huggingface_hub's
    5-retry backoff (~30 s) before falling back. An unresolvable host is
    a definitive "offline"; resolvable-but-down still goes the slow path.
    """
    import os
    import socket
    prior = socket.getdefaulttimeout()
    try:
        socket.setdefaulttimeout(timeout)
        socket.getaddrinfo("huggingface.co", 443)
        return True
    except OSError:
        # Belt and braces: transformers' adapter(PEFT) probe ignores
        # local_files_only in some versions, so force hub-offline mode
        # process-wide once we know the hub is unreachable.
        os.environ["HF_HUB_OFFLINE"] = "1"
        return False
    finally:
        socket.setdefaulttimeout(prior)

# HF model ids -> architecture configs for the random-init fallback.
_FALLBACK_CONFIGS = {
    "sshleifer/tiny-gpt2": gpt2.CONFIGS["tiny-gpt2"],
    "gpt2": gpt2.CONFIGS["gpt2"],
    "gpt2-medium": gpt2.CONFIGS["gpt2-medium"],
}


def resolve_model(cfg: ServingConfig) -> Tuple[GPT2Config, Params]:
    if cfg.checkpoint_dir:
        log.info("loading checkpoint from %s", cfg.checkpoint_dir)
        return ckpt.load(cfg.checkpoint_dir)

    try:
        # reachability check FIRST: it sets HF_HUB_OFFLINE before
        # huggingface_hub snapshots the env at import time
        offline = not hub_reachable()
        from transformers import AutoModelForCausalLM

        from ..models.hf_convert import params_from_hf_model
        model = AutoModelForCausalLM.from_pretrained(
            cfg.model_id, local_files_only=offline)
        model.eval()
        log.info("converted HF model %s", cfg.model_id)
        return params_from_hf_model(model)
    except Exception as e:  # hub unreachable / not cached / not a GPT-2
        if cfg.model_id not in _FALLBACK_CONFIGS:
            raise RuntimeError(
                f"cannot load {cfg.model_id!r}: no checkpoint dir, HF load "
                f"failed ({e}), and no fallback architecture is registered"
            ) from e
        config = _FALLBACK_CONFIGS[cfg.model_id]
        log.warning(
            "HF load of %s failed (%s); using RANDOM-INIT %s weights — "
            "output will be untrained noise", cfg.model_id, e, config)
        return config, gpt2.init_params(config, jax.random.PRNGKey(0))
