"""Model resolution for serving: checkpoint -> HF cache/hub -> random init.

Replaces the reference's import-time ``AutoModelForCausalLM.from_pretrained``
in every pod (reference server.py:40-42) with an explicit resolution order:

1. ``CHECKPOINT_DIR`` set → Orbax restore (no hub, no torch, the
   production path);
2. the HF model is loadable (cached or hub reachable) → convert through
   ``models.hf_convert`` (torch imported only here, never on the TPU
   serving path);
3. otherwise → random init from the named architecture (keeps the service
   and its wire contract alive in air-gapped test environments; logged
   loudly since generations are untrained noise — which is also true of
   the reference's default tiny-gpt2, README.md:135).
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import jax

from ..models import gpt2
from ..models.gpt2 import GPT2Config, Params
from ..utils import checkpoint as ckpt
from ..utils.config import ServingConfig

log = logging.getLogger(__name__)


def resolve_for_role(cfg: ServingConfig,
                     ) -> Tuple[GPT2Config, Optional[Params],
                                Optional[Params]]:
    """Role-aware resolution: ``(config, full_params, stage_params)`` —
    load only what this role actually serves (the reference loads the full
    model into every pod regardless of role, server.py:40-42, 108-110).

    - shard ``a``/``b`` with a dense checkpoint: TRUE partial restore of
      just that role's two-stage compat subset (``ckpt.load_stage_params``
      reads only those layers' bytes) → ``(config, None, stage)``;
    - coordinator with ``DISPATCH=remote`` and a checkpoint: the weights
      live in the shard pods; only the config is read →
      ``(config, None, None)``;
    - everything else (coordinator+local, or no checkpoint — the HF/
      random-init fallbacks produce a full tree anyway) →
      ``(config, params, None)``.
    """
    if cfg.checkpoint_dir:
        if cfg.shard_role in ("a", "b"):
            config = ckpt.load_config(cfg.checkpoint_dir)
            from ..models import is_partitionable
            if not is_partitionable(config):
                # MoE/llama stage endpoints decline every request
                # (app.py), so such a shard pod needs no weights — config
                # only
                return config, None, None
            from ..parallel import partition as P_
            specs = P_.make_stage_specs(config.n_layer, [cfg.split_at])
            idx = 0 if cfg.shard_role == "a" else 1
            log.info("partial-restoring stage %s (blocks [%d, %d)) "
                     "from %s", cfg.shard_role, specs[idx].start,
                     specs[idx].end, cfg.checkpoint_dir)
            _, stage = ckpt.load_stage_params(cfg.checkpoint_dir, specs[idx])
            return config, None, stage
        elif cfg.shard_role == "coordinator" and cfg.dispatch == "remote":
            log.info("remote-dispatch coordinator: config only from %s",
                     cfg.checkpoint_dir)
            return ckpt.load_config(cfg.checkpoint_dir), None, None
    config, params = resolve_model(cfg)
    return config, params, None


def hub_reachable(timeout: float = 1.0) -> bool:
    """Fast offline detection: can we even resolve the HF hub host?

    Without this, air-gapped startups sit through huggingface_hub's
    5-retry backoff (~30 s) before falling back. An unresolvable host is
    a definitive "offline"; resolvable-but-down still goes the slow path.
    """
    import os
    import socket
    prior = socket.getdefaulttimeout()
    try:
        socket.setdefaulttimeout(timeout)
        socket.getaddrinfo("huggingface.co", 443)
        return True
    except OSError:
        # Belt and braces: transformers' adapter(PEFT) probe ignores
        # local_files_only in some versions, so force hub-offline mode
        # process-wide once we know the hub is unreachable.
        os.environ["HF_HUB_OFFLINE"] = "1"
        return False
    finally:
        socket.setdefaulttimeout(prior)

def _fallback_configs():
    # HF model ids / family names -> architecture configs for the
    # random-init fallback (lazy so importing loader stays light).
    from ..models import llama
    return {
        "sshleifer/tiny-gpt2": gpt2.CONFIGS["tiny-gpt2"],
        "gpt2": gpt2.CONFIGS["gpt2"],
        "gpt2-medium": gpt2.CONFIGS["gpt2-medium"],
        "llama-tiny": llama.CONFIGS["llama-tiny"],
        "llama-124m": llama.CONFIGS["llama-124m"],
    }


def resolve_model(cfg: ServingConfig) -> Tuple[GPT2Config, Params]:
    if cfg.checkpoint_dir:
        log.info("loading checkpoint from %s", cfg.checkpoint_dir)
        return ckpt.load(cfg.checkpoint_dir)

    try:
        # reachability check FIRST: it sets HF_HUB_OFFLINE before
        # huggingface_hub snapshots the env at import time
        offline = not hub_reachable()
        from transformers import AutoModelForCausalLM

        from ..models.hf_convert import (llama_params_from_hf_model,
                                         params_from_hf_model)
        model = AutoModelForCausalLM.from_pretrained(
            cfg.model_id, local_files_only=offline)
        model.eval()
        log.info("converted HF model %s", cfg.model_id)
        if getattr(model.config, "model_type", "gpt2") == "llama":
            return llama_params_from_hf_model(model)
        return params_from_hf_model(model)
    except Exception as e:  # hub unreachable / not cached / not convertible
        fallbacks = _fallback_configs()
        if cfg.model_id not in fallbacks:
            raise RuntimeError(
                f"cannot load {cfg.model_id!r}: no checkpoint dir, HF load "
                f"failed ({e}), and no fallback architecture is registered"
            ) from e
        config = fallbacks[cfg.model_id]
        log.warning(
            "HF load of %s failed (%s); using RANDOM-INIT %s weights — "
            "output will be untrained noise", cfg.model_id, e, config)
        from ..models import family_module
        return config, family_module(config).init_params(
            config, jax.random.PRNGKey(0))
