"""Minimal JSON-over-HTTP framework on the Python stdlib.

The reference serves FastAPI/uvicorn (reference Dockerfile:19). This
image bakes neither, so the serving stack is self-contained: a route
table with pydantic request validation (pydantic IS available), a
threaded ``http.server`` runner for real serving, and an in-process
``TestClient`` with a requests-like API so wire-compat tests exercise
exactly the dispatch path production uses — no sockets needed.

Semantics intentionally mirror the slice of FastAPI the reference relies
on: POST handlers take one validated body model, handlers return a dict
serialized as JSON, unvalidatable bodies get HTTP 422, unknown routes
404. Role guards returning 200 + ``{"error": ...}`` therefore behave
byte-identically to the reference (server.py:135,147,157).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple, get_type_hints

import pydantic


class JSONApp:
    """Route table: (method, path) -> handler.

    POST handlers may annotate a single parameter with a pydantic
    BaseModel subclass; the body is validated into it (422 on failure).
    GET handlers take no arguments. Handlers return a JSON-serializable
    dict, or ``(status_code, dict)`` to override the 200 default.
    """

    def __init__(self, title: str = "", version: str = ""):
        self.title = title
        self.version = version
        self._routes: Dict[Tuple[str, str], Callable] = {}

    def get(self, path: str):
        return self._register("GET", path)

    def post(self, path: str):
        return self._register("POST", path)

    def _register(self, method: str, path: str):
        def deco(fn):
            self._routes[(method, path)] = fn
            return fn
        return deco

    def handle(self, method: str, path: str,
               body: Optional[bytes]) -> Tuple[int, Dict[str, Any]]:
        fn = self._routes.get((method, path))
        if fn is None:
            if any(p == path for (_, p) in self._routes):
                return 405, {"detail": "Method Not Allowed"}
            return 404, {"detail": "Not Found"}

        args = []
        hints = {k: v for k, v in get_type_hints(fn).items() if k != "return"}
        if hints:
            model = next(iter(hints.values()))
            if isinstance(model, type) and issubclass(model, pydantic.BaseModel):
                try:
                    payload = json.loads(body or b"null")
                except json.JSONDecodeError:
                    return 422, {"detail": "invalid JSON body"}
                try:
                    args.append(model.model_validate(payload))
                except pydantic.ValidationError as e:
                    return 422, {"detail": json.loads(e.json())}
        try:
            result = fn(*args)
        except Exception as e:  # uncaught handler error -> 500, like uvicorn
            return 500, {"detail": f"{type(e).__name__}: {e}"}
        if (isinstance(result, tuple) and len(result) == 2
                and isinstance(result[0], int)):
            return result
        return 200, result  # payload: dict (JSON) or str (text/plain)


class Response:
    """requests-compatible view of a handled call."""

    def __init__(self, status_code: int, payload: Any):
        self.status_code = status_code
        self._payload = payload
        self.text = payload if isinstance(payload, str) else json.dumps(payload)

    def json(self) -> Dict[str, Any]:
        if isinstance(self._payload, str):
            raise ValueError("response is text, not JSON")
        return self._payload

    def raise_for_status(self) -> None:
        if self.status_code >= 400:
            raise RuntimeError(f"HTTP {self.status_code}: {self.text}")


class TestClient:
    """In-process client running the exact server dispatch path."""

    __test__ = False  # not a pytest collection target

    def __init__(self, app: JSONApp):
        self.app = app

    def get(self, path: str) -> Response:
        return Response(*self.app.handle("GET", path, None))

    def post(self, path: str, json: Any = None) -> Response:  # noqa: A002
        import json as _json
        return Response(*self.app.handle(
            "POST", path, _json.dumps(json).encode()))


def serve(app: JSONApp, host: str = "0.0.0.0", port: int = 5000,
          block: bool = True) -> ThreadingHTTPServer:
    """Serve over real sockets (threaded, one request per thread).

    With ``block=False`` the server runs on a daemon thread and is
    returned so callers (tests, embedders) can ``.shutdown()`` it.
    """

    class Handler(BaseHTTPRequestHandler):
        def _dispatch(self, method: str):
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else None
            status, payload = app.handle(method, self.path, body)
            if isinstance(payload, str):
                data = payload.encode()
                ctype = "text/plain; version=0.0.4"  # Prometheus exposition
            else:
                data = json.dumps(payload).encode()
                ctype = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 (stdlib naming)
            self._dispatch("GET")

        def do_POST(self):  # noqa: N802
            self._dispatch("POST")

        def log_message(self, fmt, *args):  # route through logging, quieter
            import logging
            logging.getLogger("llm_sharding_demo_tpu.serving").info(
                "%s %s", self.address_string(), fmt % args)

    server = ThreadingHTTPServer((host, port), Handler)
    if block:
        server.serve_forever()
        return server
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
