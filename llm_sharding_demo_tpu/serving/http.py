"""Minimal JSON-over-HTTP framework on the Python stdlib.

The reference serves FastAPI/uvicorn (reference Dockerfile:19). This
image bakes neither, so the serving stack is self-contained: a route
table with pydantic request validation (pydantic IS available), a
threaded ``http.server`` runner for real serving, and an in-process
``TestClient`` with a requests-like API so wire-compat tests exercise
exactly the dispatch path production uses — no sockets needed.

Semantics intentionally mirror the slice of FastAPI the reference relies
on: POST handlers take one validated body model, handlers return a dict
serialized as JSON, unvalidatable bodies get HTTP 422, unknown routes
404. Role guards returning 200 + ``{"error": ...}`` therefore behave
byte-identically to the reference (server.py:135,147,157).

Handlers may additionally declare parameters by NAME to receive request
context (both optional, so existing handlers are untouched):

- ``headers``: the request headers as a lower-cased dict (request-ID
  propagation reads ``x-request-id`` here);
- ``query``: the parsed query string as a flat dict (last value wins) —
  ``/debug/requests?slowest=1`` style options.

Handlers return a dict (200), ``(status, payload)``, or ``(status,
payload, headers)`` — the third form sets response headers (the echoed
``X-Request-ID``).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple, get_type_hints
from urllib.parse import parse_qsl, urlsplit

import pydantic

# handler parameters passed by NAME (never body-validated)
_CONTEXT_PARAMS = ("headers", "query")

# Lock-discipline contract (tools/graftcheck locks pass): the server is
# intentionally lock-free — the route table is frozen before serve()
# spawns its threads, and all per-request state is handler-local.
# Declared empty so a lock added here must declare what it protects.
GUARDED_STATE = {}
LOCK_ORDER = ()

# Fault contract (tools/graftcheck faults pass): the dispatch layer owns
# NO blocking boundaries — socket reads ride the stdlib server and every
# handler failure is already a typed 4xx/500 (degraded-mode headers like
# Retry-After flow through the 3-tuple handler return). Declared empty
# so a blocking call added here must declare its policy.
FAULT_POLICY = {}


class JSONApp:
    """Route table: (method, path) -> handler.

    POST handlers may annotate a single parameter with a pydantic
    BaseModel subclass; the body is validated into it (422 on failure).
    GET handlers take no body. Handlers return a JSON-serializable
    dict, ``(status_code, dict)``, or ``(status_code, dict, headers)``.
    """

    def __init__(self, title: str = "", version: str = ""):
        self.title = title
        self.version = version
        self._routes: Dict[Tuple[str, str], Callable] = {}

    def get(self, path: str):
        return self._register("GET", path)

    def post(self, path: str):
        return self._register("POST", path)

    def _register(self, method: str, path: str):
        def deco(fn):
            self._routes[(method, path)] = fn
            return fn
        return deco

    def handle(self, method: str, path: str, body: Optional[bytes],
               headers: Optional[Dict[str, str]] = None,
               ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        parts = urlsplit(path)
        route_path = parts.path
        fn = self._routes.get((method, route_path))
        if fn is None:
            if any(p == route_path for (_, p) in self._routes):
                return 405, {"detail": "Method Not Allowed"}, {}
            return 404, {"detail": "Not Found"}, {}

        kwargs: Dict[str, Any] = {}
        code = getattr(fn, "__code__", None)
        arg_names = (code.co_varnames[:code.co_argcount] if code else ())
        if "headers" in arg_names:
            kwargs["headers"] = {k.lower(): v
                                 for k, v in (headers or {}).items()}
        if "query" in arg_names:
            kwargs["query"] = dict(parse_qsl(parts.query))

        args = []
        hints = {k: v for k, v in get_type_hints(fn).items()
                 if k != "return" and k not in _CONTEXT_PARAMS}
        if hints:
            model = next(iter(hints.values()))
            if isinstance(model, type) and issubclass(model, pydantic.BaseModel):
                try:
                    payload = json.loads(body or b"null")
                except json.JSONDecodeError:
                    return 422, {"detail": "invalid JSON body"}, {}
                try:
                    args.append(model.model_validate(payload))
                except pydantic.ValidationError as e:
                    return 422, {"detail": json.loads(e.json())}, {}
        try:
            result = fn(*args, **kwargs)
        except Exception as e:  # uncaught handler error -> 500, like uvicorn
            return 500, {"detail": f"{type(e).__name__}: {e}"}, {}
        if isinstance(result, tuple) and len(result) == 3 \
                and isinstance(result[0], int):
            return result
        if (isinstance(result, tuple) and len(result) == 2
                and isinstance(result[0], int)):
            return result[0], result[1], {}
        return 200, result, {}  # payload: dict (JSON) or str (text/plain)


class Response:
    """requests-compatible view of a handled call."""

    def __init__(self, status_code: int, payload: Any,
                 headers: Optional[Dict[str, str]] = None):
        self.status_code = status_code
        self._payload = payload
        self.headers = dict(headers or {})
        self.text = payload if isinstance(payload, str) else json.dumps(payload)

    def json(self) -> Dict[str, Any]:
        if isinstance(self._payload, str):
            raise ValueError("response is text, not JSON")
        return self._payload

    def raise_for_status(self) -> None:
        if self.status_code >= 400:
            raise RuntimeError(f"HTTP {self.status_code}: {self.text}")


class TestClient:
    """In-process client running the exact server dispatch path.

    ``timeout_s`` exists for wire parity with a socket-backed client
    (the fleet router derives it from the request's remaining
    X-Deadline-Ms budget per hop): in-process dispatch is synchronous
    and bounded by the replica's OWN deadline enforcement — the budget
    also travels in-band as the X-Deadline-Ms header — so the argument
    is accepted and unused here, while a requests-backed adapter
    passes it through as the socket timeout.
    """

    __test__ = False  # not a pytest collection target

    def __init__(self, app: JSONApp):
        self.app = app

    def get(self, path: str,
            headers: Optional[Dict[str, str]] = None,
            timeout_s: Optional[float] = None) -> Response:
        return Response(*self.app.handle("GET", path, None, headers))

    def post(self, path: str, json: Any = None,  # noqa: A002
             headers: Optional[Dict[str, str]] = None,
             timeout_s: Optional[float] = None) -> Response:
        import json as _json
        return Response(*self.app.handle(
            "POST", path, _json.dumps(json).encode(), headers))


def serve(app: JSONApp, host: str = "0.0.0.0", port: int = 5000,
          block: bool = True) -> ThreadingHTTPServer:
    """Serve over real sockets (threaded, one request per thread).

    With ``block=False`` the server runs on a daemon thread and is
    returned so callers (tests, embedders) can ``.shutdown()`` it.
    """

    class Handler(BaseHTTPRequestHandler):
        def _dispatch(self, method: str):
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else None
            status, payload, resp_headers = app.handle(
                method, self.path, body, dict(self.headers.items()))
            if isinstance(payload, str):
                data = payload.encode()
                ctype = "text/plain; version=0.0.4"  # Prometheus exposition
            else:
                data = json.dumps(payload).encode()
                ctype = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in resp_headers.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 (stdlib naming)
            self._dispatch("GET")

        def do_POST(self):  # noqa: N802
            self._dispatch("POST")

        def log_message(self, fmt, *args):  # route through logging, quieter
            import logging
            logging.getLogger("llm_sharding_demo_tpu.serving").info(
                "%s %s", self.address_string(), fmt % args)

    server = ThreadingHTTPServer((host, port), Handler)
    if block:
        server.serve_forever()
        return server
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
