"""HTTP serving surface — wire-compatible with the reference.

Routes, schemas, role guards, and response shapes mirror reference
server.py:116-210 exactly:

- ``POST /forward``    {"input_ids": [int]}        -> {"hidden_states": [[[f]]]}
- ``POST /forward_b``  {"hidden_states": [[[f]]]}  -> {"logits": [[[f]]]}
- ``POST /generate``   {"prompt", "max_new_tokens"} -> {"generated": str}
- role guards return HTTP 200 with ``{"error": "This instance is not
  ..."}`` — preserved verbatim for wire parity even though it's a
  reference quirk (SURVEY.md §2.3.5: its coordinator's raise_for_status
  never fires on misrouting);

plus what the reference lacks:

- ``GET /healthz`` readiness/liveness (SURVEY.md §5 "Failure detection":
  the reference ships no probes, so k8s cannot tell a wedged pod from a
  healthy one);
- N-stage local dispatch: the common-case pod owns its TPU devices and
  runs the whole pipeline on-device (``parallel.pipeline``); ``DISPATCH=
  remote`` reproduces the reference's three-pod HTTP topology for
  drop-in k8s compatibility (coordinator POSTs to shard services per
  token, reference server.py:169-181);
- request-level decode controls: the reference hard-codes
  temperature=0.6/top_k=40 sampling (server.py:187-205); here that is the
  default, with optional ``mode="greedy"`` (BASELINE.json's parity mode)
  and an explicit ``seed`` for reproducibility.
"""

from __future__ import annotations

import logging
import re
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import BaseModel

from ..models import gpt2
from ..parallel import partition as P_
from ..parallel.pipeline import PipelineRunner
from ..runtime.engine import REF_TEMPERATURE, REF_TOP_K, SamplingConfig
from ..utils import graftfault, graftmem, graftshard, grafttime, \
    grafttrend, tracing
from ..utils.config import ServingConfig, from_env
from ..utils.metrics import REGISTRY
from ..utils.tracing import timed
from . import loader
from .http import JSONApp
from .tokenizer import get_tokenizer

log = logging.getLogger(__name__)

# Lock-discipline contract (tools/graftcheck locks pass): this module
# runs on ThreadingHTTPServer handler threads but owns NO locks — every
# shared object a handler touches (runner/pool/registry/recorder)
# guards its own state (see those modules' GUARDED_STATE). Declared
# empty so a lock added here must declare what it protects.
GUARDED_STATE = {}
LOCK_ORDER = ()

# Fault contract (tools/graftcheck faults pass): the coordinator's one
# blocking boundary is the remote-dispatch shard hop. Its per-attempt
# timeout derives from the request's remaining deadline budget
# (X-Deadline-Ms) capped by the HopPolicy's per-attempt budget; retries
# ride the typed policy (capped exponential backoff + jitter, per-shard
# circuit breaker); failure degrades to a typed 502 (upstream) or 503 +
# Retry-After (breaker open / deadline exhausted) — never an opaque 500.
FAULT_POLICY = {
    "requests.post": ("request", "hop-policy",
                      "typed 502/503 + Retry-After, per-shard breaker"),
}


class UpstreamError(Exception):
    """A shard hop failed (connection, HTTP error, or error body)."""

    def __init__(self, shard: str, url: str, detail: str):
        super().__init__(f"shard {shard} at {url}: {detail}")
        self.shard = shard
        self.url = url
        self.detail = detail


class InputIDs(BaseModel):
    input_ids: List[int]


class PrefillReq(BaseModel):
    """graftfleet /prefill body: just the prompt — the prefill replica
    fills shared pool blocks; block ids never cross the wire."""

    prompt: str


class HiddenStates(BaseModel):
    hidden_states: list  # nested [batch, seq, hidden]


class GenerateReq(BaseModel):
    prompt: str
    max_new_tokens: int = 20
    # extensions beyond the reference schema (defaults reproduce its
    # behavior: temperature-0.6/top-k-40 sampling)
    mode: str = "sample"
    temperature: float = REF_TEMPERATURE
    top_k: int = REF_TOP_K
    # nucleus sampling within the top-k survivors; 1.0 = off (pure
    # reference math)
    top_p: float = 1.0
    # stop early (truncate) at the tokenizer's EOS token, or at an
    # explicit ``eos_token_id``. Off by default: the reference always
    # emits exactly max_new_tokens (server.py:169), so parity mode does
    # too.
    stop_at_eos: bool = False
    eos_token_id: Optional[int] = None
    # Seed reproducibility contract: the same (prompt, params, seed) on
    # the SAME server configuration replays the same stream. Across
    # configurations the stream may legitimately differ while the
    # distribution does not: SPEC_DECODE>0 routes sample-mode requests
    # through the rejection-sampled speculative engine, whose RNG
    # consumption pattern differs from the plain scan's (and from the
    # reference's unseeded torch sampler, SURVEY.md §7(d)). Don't key
    # golden outputs on seeds across serving-config changes.
    seed: Optional[int] = None


# -- request-identity / deadline header parsing (shared by /generate,
# -- /prefill, and the fleet router — ONE charset and ONE budget bound,
# -- so a future widening cannot land in one copy and miss the others)

_RID_RE = re.compile(r"[A-Za-z0-9._:-]{1,128}")
_PROFILE_RE = re.compile(r"[A-Za-z0-9._:-]{1,64}")
DEADLINE_MS_ERROR = ("X-Deadline-Ms must be an integer millisecond "
                     "budget in [1, 86400000]")


def parse_request_identity(headers: dict) -> Tuple[str, Optional[str]]:
    """(rid, profile_label): honor a caller's X-Request-ID, mint one
    otherwise; both values restricted to a safe charset — they are
    interpolated into log lines, echoed as headers, and query-matched
    verbatim (the same injection class _escape_label_value fixes for
    /metrics)."""
    raw_rid = (headers.get("x-request-id") or "").strip()
    rid = (raw_rid if _RID_RE.fullmatch(raw_rid)
           else tracing.new_request_id())
    raw_prof = (headers.get("x-workload-profile") or "").strip()
    return rid, (raw_prof if _PROFILE_RE.fullmatch(raw_prof) else None)


def parse_deadline_header(headers: dict):
    """X-Deadline-Ms -> (deadline, dl_ms, error): (None, None, None)
    when absent, (None, None, msg) on a malformed/out-of-range value
    (callers answer 400 — this header is an extension, so
    status-checking clients get the honest signal; parity only binds
    the reference's own fields)."""
    raw_dl = (headers.get("x-deadline-ms") or "").strip()
    if not raw_dl:
        return None, None, None
    try:
        dl_ms = int(raw_dl)
    except ValueError:
        dl_ms = 0
    if not 1 <= dl_ms <= 86_400_000:
        return None, None, DEADLINE_MS_ERROR
    return graftfault.Deadline.from_ms(dl_ms), dl_ms, None


def create_app(cfg: Optional[ServingConfig] = None,
               model=None, tokenizer=None,
               registry=None, recorder=None, kv_pool=None,
               replica: Optional[str] = None) -> JSONApp:
    """Build the app. ``model=(config, params)`` / ``tokenizer`` injectable
    for tests; by default resolved via ``serving.loader`` / HF-or-byte
    tokenizer. ``registry`` (utils.metrics.MetricsRegistry) and
    ``recorder`` (utils.tracing.FlightRecorder) are likewise injectable —
    tests can assert the app-level series/traces without touching the
    process-global defaults. ``kv_pool`` (a ``runtime.kv_pool.
    KVBlockPool`` matching this app's engine geometry) makes this
    replica serve off a SHARED pool instead of building its own — the
    graftfleet process-local form, where prefill and decode replicas
    hand blocks off through one allocator's content-keyed registry.
    ``replica`` labels this app's request-scoped timeline events
    (grafttime's replica correlator — the fleet harness passes the
    replica name); defaults to the fleet role, or "solo"."""
    cfg = cfg or from_env()
    replica_label = replica or cfg.fleet_role or "solo"
    reg = registry if registry is not None else REGISTRY
    rec = recorder if recorder is not None else tracing.RECORDER
    # Trend & drift watch (utils/grafttrend): one reducer per app,
    # folded over THIS app's registry — the poll-on-read loop (every
    # GET /debug/trend taps the producers and evaluates the declared
    # WATCH_POLICY), plus the wave-boundary tap when continuous
    # planning attaches it below.
    trend_reducer = grafttrend.TrendReducer(registry=reg)
    # multi-host glue sits HERE, where every entry path converges (CLI,
    # `serving.app:app` lazy attribute, tests) — it must run before the
    # first backend use, i.e. before the model loads. No-op when the
    # COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID contract is unset.
    from ..parallel.distributed import maybe_initialize
    maybe_initialize()
    # Role-aware loading: shard pods with a checkpoint partial-restore only
    # their stage's layers (utils.checkpoint.load_stage_params); a
    # remote-dispatch coordinator reads config only. ``params`` is None in
    # those cases and ``stage_only`` holds a shard role's subset.
    if model is not None:
        config, params = model
        stage_only = None
    else:
        config, params, stage_only = loader.resolve_for_role(cfg)
    tokenizer = tokenizer or get_tokenizer(cfg.model_id,
                                           checkpoint_dir=cfg.checkpoint_dir)

    # AUTO_PLAN (tools/graftcheck/costmodel): resolve the decode
    # topology/batching/KV knobs at startup from the compile-free cost
    # model — every candidate is gated through the graftcheck semantic
    # verifier before scoring, so a plan this block installs is exactly
    # as validated as a hand-written one (the guards below still run on
    # the resolved values). The chosen plan is logged and reported
    # under /healthz "auto_plan".
    auto_plan_info = None
    if cfg.auto_plan:
        if not (cfg.shard_role == "coordinator" and cfg.dispatch == "local"):
            raise ValueError("AUTO_PLAN applies to the coordinator's local "
                             "decode path only")
        try:
            from tools.graftcheck import costmodel as _cm
        except ImportError as e:
            raise ValueError(
                "AUTO_PLAN=1 needs the repo's tools/ package importable "
                "(run from the repo checkout root)") from e
        plan_traffic = (_cm.parse_traffic(cfg.auto_plan_traffic)
                        if cfg.auto_plan_traffic else None)
        payload = _cm.plan_for_serving(
            config, len(jax.devices()), max_seq=cfg.max_seq,
            traffic=plan_traffic, max_batch_cap=max(cfg.max_batch, 1),
            kv_pool_blocks=cfg.kv_pool_blocks,
            kv_block_size=cfg.kv_block_size)
        chosen = payload["chosen"]
        if chosen is None:
            raise ValueError(
                "AUTO_PLAN: no candidate serving config survived the "
                "graftcheck verifier for this model/mesh/traffic")
        import dataclasses as _dc
        c = chosen["config"]
        cfg = _dc.replace(
            cfg,
            batch_mode=c["batch_mode"], max_batch=c["max_batch"],
            kv_pool_blocks=c["kv_pool_blocks"],
            kv_block_size=c["kv_block_size"],
            pp_decode=c["topology"] == "pp",
            tp_decode=c["topology"] == "tp",
            ep_decode=c["topology"] == "ep",
            boundaries=(tuple(c["boundaries"]) if c["topology"] == "pp"
                        else cfg.boundaries))
        auto_plan_info = {
            "chosen": chosen["label"],
            "mesh": chosen.get("mesh", {}),
            "cost_per_token": chosen["cost_per_token"],
            "comm_bytes_per_token": chosen["comm_bytes_per_token"],
            "hbm_bytes_per_device": chosen["hbm_bytes_per_device"],
            "programs_exact": chosen["programs_exact"],
            "candidates": len(payload["plan"]),
            "rejected": payload["rejected"],
        }
        log.info('{"event": "auto_plan", "chosen": "%s", '
                 '"cost_per_token": %s, "candidates": %d, "rejected": %d}',
                 chosen["label"], chosen["cost_per_token"],
                 len(payload["plan"]), payload["rejected"])

    n_layer = config.n_layer
    for b in cfg.boundaries:
        if not 1 <= b <= n_layer - 1:
            raise ValueError(
                f"boundary {b} out of range for n_layer={n_layer}")

    # Build only what this role serves (the reference loads the full model
    # into every pod regardless of role, server.py:108-110 — the exact
    # memory waste this gate avoids):
    # - coordinator + local dispatch: the N-stage pipeline for /generate;
    # - roles a/b: their half of the two-stage compat view for /forward +
    #   /forward_b — the reference's ShardA/ShardB contract
    #   (server.py:51-105) regardless of how many stages /generate uses;
    # - coordinator + remote dispatch: nothing (shards hold the weights).
    from ..models import is_partitionable, is_stage_partitionable
    # Two distinct notions: ``partitionable`` is the reference's GPT-2
    # WIRE topology (/forward + /forward_b relay, remote dispatch) —
    # GPT-2-only by design; ``stageable`` is whether the decode engine
    # can stage the family at all (GPT-2 and llama; MoE decodes
    # unstaged).
    partitionable = is_partitionable(config)
    stageable = is_stage_partitionable(config)
    if not partitionable and cfg.dispatch == "remote":
        # the remote topology relays hidden states between stage shards
        # (/forward -> /forward_b), which non-GPT-2 pods decline —
        # /generate would die on a KeyError mid-relay; fail at startup
        raise ValueError(
            "DISPATCH=remote requires the dense GPT-2 stage-shard "
            f"topology; {type(config).__name__} models serve with "
            "DISPATCH=local")
    if cfg.inference_dtype != "float32" and not (
            cfg.shard_role == "coordinator" and cfg.dispatch == "local"):
        # only the local decode runner implements the fast dtypes; a
        # silently-ignored knob with /healthz still reporting it would
        # tell monitoring the fleet is quantized when it is not
        raise ValueError(
            f"INFERENCE_DTYPE={cfg.inference_dtype} applies to the "
            "coordinator's local decode path only; shard/remote roles "
            "serve the fp32 parity endpoints")
    if cfg.spec_decode > 0 and not (
            cfg.shard_role == "coordinator" and cfg.dispatch == "local"):
        raise ValueError(
            f"SPEC_DECODE={cfg.spec_decode} applies to the coordinator's "
            "local decode path only")
    if cfg.prefix_cache > 0:
        if not (cfg.shard_role == "coordinator" and cfg.dispatch == "local"):
            raise ValueError(
                f"PREFIX_CACHE={cfg.prefix_cache} applies to the "
                "coordinator's local decode path only")
        # prefix+batching composes (per-row store prefills merged into
        # one batched decode in admission mode, store-backed admission
        # prefills in iter mode), and prefix+speculation composes
        # single-stream AND batched (spec-flagged rounds/batches decode
        # through the batched verify loop).
    if cfg.ep_decode:
        if not (cfg.shard_role == "coordinator" and cfg.dispatch == "local"):
            raise ValueError("EP_DECODE applies to the coordinator's local "
                             "decode path only")
        if not hasattr(config, "n_experts"):
            raise ValueError(
                f"EP_DECODE shards MoE expert weights; "
                f"{type(config).__name__} models have no expert axis")
        if cfg.pp_decode or cfg.spec_decode > 0 or cfg.prefix_cache > 0:
            raise ValueError(
                "EP_DECODE composes with MAX_BATCH only; PP_DECODE, "
                "SPEC_DECODE, and PREFIX_CACHE own other decode programs "
                "(and MoE prefills monolithically — no PREFILL_CHUNK)")
        ep_size = min(len(jax.devices()), config.n_experts)
        if config.n_experts % ep_size:
            raise ValueError(
                f"EP_DECODE: n_experts={config.n_experts} not divisible "
                f"by the {ep_size}-device ep axis")
    if cfg.kv_pool_blocks > 0:
        if not (cfg.shard_role == "coordinator" and cfg.dispatch == "local"):
            raise ValueError("KV_POOL_BLOCKS applies to the coordinator's "
                             "local decode path only")
        if cfg.pp_decode or cfg.ep_decode or cfg.tp_decode:
            raise ValueError(
                "KV_POOL_BLOCKS drives the single-device engine's paged "
                "storage; PP/EP/TP_DECODE keep contiguous caches")
        if cfg.prefill_chunk > 0:
            raise ValueError(
                "KV_POOL_BLOCKS prefills monolithically (one block "
                "scatter per admission); PREFILL_CHUNK owns another "
                "prefill program structure")
        if cfg.spec_decode > 0 and cfg.batch_mode != "iter":
            raise ValueError(
                "KV_POOL_BLOCKS composes with SPEC_DECODE through "
                "BATCH_MODE=iter (paged draft-verify segments); the "
                "solo paged runner decodes one token per forward")
        if cfg.max_batch > 1 and cfg.batch_mode != "iter":
            raise ValueError(
                "KV_POOL_BLOCKS batches through BATCH_MODE=iter "
                "(watermark admission + preemption live at segment "
                "boundaries); the admission batcher keeps contiguous "
                "round caches")
        from ..models import is_window_independent as _wi
        if not _wi(config):
            raise ValueError(
                "KV_POOL_BLOCKS requires window-independent routing "
                f"(dense families); {type(config).__name__} serves "
                "unpaged")
    if cfg.batch_mode == "iter":
        if cfg.max_batch <= 1:
            raise ValueError("BATCH_MODE=iter requires MAX_BATCH > 1 "
                             "(iteration-level scheduling is a batching "
                             "policy)")
        if not (cfg.shard_role == "coordinator" and cfg.dispatch == "local"):
            raise ValueError("BATCH_MODE=iter applies to the coordinator's "
                             "local decode path only")
        if (cfg.prefill_chunk > 0 or cfg.pp_decode
                or cfg.ep_decode or cfg.tp_decode):
            # SPEC_DECODE composes (draft-verify segments) and
            # PREFIX_CACHE composes (store-backed admission prefills);
            # chunked prefill and the mesh/pipeline decoders still own
            # other program structures
            raise ValueError(
                "BATCH_MODE=iter drives the single-device engine's "
                "segment loop; PREFILL_CHUNK/PP/EP/TP_DECODE use "
                "BATCH_MODE=admission")
        from ..models import is_window_independent
        if not is_window_independent(config):
            raise ValueError(
                "BATCH_MODE=iter requires window-independent routing "
                f"(dense families); {type(config).__name__} batches via "
                "BATCH_MODE=admission")
    if cfg.tp_decode:
        if not (cfg.shard_role == "coordinator" and cfg.dispatch == "local"):
            raise ValueError("TP_DECODE applies to the coordinator's local "
                             "decode path only")
        if hasattr(config, "n_experts"):
            raise ValueError(
                "TP_DECODE shards dense-family projections; MoE models "
                "shard their expert axis via EP_DECODE instead")
        if (cfg.pp_decode or cfg.ep_decode or cfg.spec_decode > 0
                or cfg.prefix_cache > 0):
            raise ValueError(
                "TP_DECODE composes with MAX_BATCH and PREFILL_CHUNK "
                "only; PP_DECODE/EP_DECODE/SPEC_DECODE/PREFIX_CACHE own "
                "other decode programs")
        if cfg.inference_dtype == "int8":
            raise ValueError(
                "TP_DECODE runs fp32/bf16 (the int8 streaming matmuls "
                "are unpartitioned Pallas kernels GSPMD cannot split)")
        tp_size = len(jax.devices())
        kv_heads = getattr(config, "n_kv_head", config.n_head)
        if config.n_head % tp_size or kv_heads % tp_size:
            raise ValueError(
                f"TP_DECODE: this pod's {tp_size} devices must divide "
                f"n_head={config.n_head} and n_kv_head={kv_heads} "
                "(attention shards over whole heads)")
    if cfg.pp_decode:
        if not (cfg.shard_role == "coordinator" and cfg.dispatch == "local"):
            raise ValueError("PP_DECODE applies to the coordinator's local "
                             "decode path only")
        if not stageable:
            raise ValueError(
                f"PP_DECODE requires a stage-partitionable family; "
                f"{type(config).__name__} models decode unstaged")
        if cfg.spec_decode > 0 or cfg.prefix_cache > 0 or cfg.prefill_chunk > 0:
            # round 3 lifted the rest of the round-2 exclusivity wall:
            # int8 stage weights and (ragged) batching now compose with
            # the ppermute program (parallel.ppdecode); speculation,
            # prefix caching, and chunked prefill still own the
            # single-device engine's prefill/decode program structure
            raise ValueError(
                "PP_DECODE composes with MAX_BATCH>1 and "
                "INFERENCE_DTYPE=int8; SPEC_DECODE, PREFIX_CACHE, and "
                "PREFILL_CHUNK own the single-device engine's programs")
        n_stages_cfg = len(cfg.boundaries) + 1
        if len(jax.devices()) < n_stages_cfg:
            raise ValueError(
                f"PP_DECODE needs >= {n_stages_cfg} devices (one per "
                f"stage); this pod sees {len(jax.devices())}")
    runner = None
    spec_runner = None
    prefix_runner = None   # closure target for /prefill's role guard
    switcher = None        # graftwatch continuous-mode plan switcher
    # ``kv_pool`` is the (optional) injected shared pool; non-pooled
    # configurations must not carry one (validated below), and only the
    # coordinator's local decode path can host it at all
    if kv_pool is not None and not (cfg.shard_role == "coordinator"
                                    and cfg.dispatch == "local"):
        raise ValueError("kv_pool injection applies to the "
                         "coordinator's local decode path only")
    # What /healthz reports as n_stages: the decode topology actually
    # serving /generate, not just the configured partition — a monitoring
    # read of "3 stages" while an unstaged engine answers requests is the
    # same silent-knob misreport the INFERENCE_DTYPE guard above refuses.
    decode_stages = len(cfg.boundaries) + 1
    if cfg.shard_role == "coordinator" and cfg.dispatch == "local":
        # the validated dtype name passes straight through: astype/zeros
        # accept dtype strings and the engine branches on "int8" itself
        dtype = cfg.inference_dtype
        # chunked prefill bounds compile count per prompt length; 0 -> off
        pchunk = cfg.prefill_chunk or None
        if cfg.auto_plan_continuous:
            # Continuous re-planning (utils/graftwatch, the dynamic
            # half of the graftcheck watch pass): ONE engine and ONE
            # block pool back a PRE-CERTIFIED switchable plan set —
            # the solo paged runner and the pooled iteration
            # scheduler, both built HERE, at startup. The switcher
            # only ever re-routes admissions between these front ends
            # (it can never construct a runner), which is the whole
            # "a plan switch causes zero recompiles beyond the
            # certified set" invariant; the certified program cost of
            # each plan is proven through recompile.certify machinery
            # in graftwatch.certify_plan_set and served at
            # GET /debug/plan. Composition exclusions live in
            # utils.config (__post_init__).
            from ..models import is_window_independent as _wi_c
            if not _wi_c(config):
                raise ValueError(
                    "AUTO_PLAN_CONTINUOUS requires window-independent "
                    f"routing (dense families); {type(config).__name__} "
                    "serves hand-tuned")
            try:
                from ..utils import graftwatch
                import tools.graftcheck  # noqa: F401 — certifier dep
            except ImportError as e:
                raise ValueError(
                    "AUTO_PLAN_CONTINUOUS needs the repo's tools/ "
                    "package importable (run from the repo checkout "
                    "root) — the plan set is certified through "
                    "tools/graftcheck") from e
            from ..runtime.engine import DecodeEngine
            engine = DecodeEngine(params, config, max_seq=cfg.max_seq,
                                  dtype=dtype)
            decode_stages = 1
            if kv_pool is not None:
                if kv_pool.max_seq != engine._cache_seq:
                    raise ValueError(
                        f"injected kv_pool spans {kv_pool.max_seq} "
                        f"slots, engine cache is {engine._cache_seq} — "
                        "shared-pool replicas must agree on geometry")
                if kv_pool.block_dtype is not None:
                    # config already refuses KV_POOL_DTYPE under
                    # continuous mode; an injected pool must not smuggle
                    # quantized movers past the certified plan set
                    raise ValueError(
                        f"injected kv_pool stores {kv_pool.block_regime} "
                        "blocks — AUTO_PLAN_CONTINUOUS certifies the "
                        "full-precision mover programs only")
            else:
                from ..runtime.kv_pool import KVBlockPool
                kv_pool = KVBlockPool.for_engine(
                    engine, num_blocks=cfg.kv_pool_blocks,
                    block_size=cfg.kv_block_size)
            weights = graftwatch.CostWeights.apriori()
            if cfg.auto_plan_journal:
                # telemetry-calibrated byte weights: the journaled
                # graftscope_attribution drift rows (and the ICI
                # calibration row) re-price the live scoring with this
                # host's measured rates. A malformed journal raises the
                # typed CalibrationError at startup — never a silent
                # fall-back to the a-priori weights.
                import json as _json
                with open(cfg.auto_plan_journal, encoding="utf-8") as f:
                    weights = graftwatch.fit_cost_weights(_json.load(f))
            plans, plan_cost_map, certified = graftwatch.build_plan_set(
                engine, kv_pool, config, max_seq=cfg.max_seq,
                max_batch=cfg.max_batch,
                traffic=cfg.auto_plan_traffic or None,
                batch_wait_ms=cfg.batch_wait_ms)
            watcher = graftwatch.TelemetryWatcher(registry=reg)
            switcher = graftwatch.PlanSwitcher(
                plans, plan_cost_map, certified, watcher,
                weights=weights, registry=reg)
            # between waves the switcher polls the trend reducer and
            # sizes the declared SIZING_POLICY knobs from its windowed
            # occupancy estimate (zero-recompile, byte-equal — see
            # graftwatch.attach_trend)
            switcher.attach_trend(trend_reducer)
            log.info('{"event": "auto_plan_continuous", "plans": %s, '
                     '"active": "%s", "weights": "%s"}',
                     sorted(plans), switcher.health_view()["active"],
                     weights.source)
        elif cfg.spec_decode > 0:
            # prompt-lookup speculation (runtime.spec_decode):
            # single-stream requests emit up to draft_len+1 tokens per
            # forward — token-exact for greedy, distribution-exact for
            # sample mode; requests that don't fit speculation's guards
            # fall through to the wrapped plain engine (same weights).
            # The spec engine decodes unstaged (one program, one device
            # group) — reflected in decode_stages below.
            from ..runtime.spec_decode import SpecDecodeEngine
            spec_runner = SpecDecodeEngine(params, config,
                                           max_seq=cfg.max_seq, dtype=dtype,
                                           draft_len=cfg.spec_decode,
                                           prefill_chunk=pchunk)
            runner = spec_runner.plain
            decode_stages = 1
        elif not stageable:
            # MoE's expert tree isn't stage-partitionable; the whole
            # model decodes as one program on the pod's devices
            # (models.family_module dispatch in the engine). EP_DECODE
            # shards the expert stack over an ep mesh axis spanning the
            # pod's devices (validated above).
            from ..runtime.engine import DecodeEngine
            mesh = None
            if cfg.ep_decode:
                from ..parallel.spmd import make_mesh
                ep_size = min(len(jax.devices()), config.n_experts)
                mesh = make_mesh({"ep": ep_size}, jax.devices()[:ep_size])
            runner = DecodeEngine(params, config, max_seq=cfg.max_seq,
                                  dtype=dtype, prefill_chunk=pchunk,
                                  mesh=mesh)
            decode_stages = 1  # unstaged (no dense partition)
        elif cfg.pp_decode:
            # one stage per device, activations hop the ICI ring inside
            # a single compiled program per phase (parallel.ppdecode) —
            # the TPU-native endgame of the reference's per-token HTTP
            # topology. Composes with int8 stage weights, uneven
            # BOUNDARIES (padded stacking), and MAX_BATCH>1 (the batcher
            # wraps below; ragged rows ride per-row pad masks).
            from ..parallel.ppdecode import PipelinedDecoder
            from ..parallel.spmd import make_mesh
            n_st = len(cfg.boundaries) + 1
            mesh = make_mesh({"pp": n_st}, jax.devices()[:n_st])
            runner = PipelinedDecoder(params, config, mesh,
                                      max_seq=cfg.max_seq, dtype=dtype,
                                      boundaries=list(cfg.boundaries))
        elif cfg.tp_decode:
            # tensor-parallel single-stream decode: Megatron column/row
            # projections + head-sharded KV cache over a tp mesh spanning
            # the pod's devices (runtime.engine._place_tp_params);
            # composes with MAX_BATCH (the batcher wraps below) and
            # PREFILL_CHUNK. Divisibility validated above.
            from ..parallel.spmd import make_mesh
            from ..runtime.engine import DecodeEngine
            mesh = make_mesh({"tp": len(jax.devices())}, jax.devices())
            runner = DecodeEngine(params, config, max_seq=cfg.max_seq,
                                  dtype=dtype, prefill_chunk=pchunk,
                                  mesh=mesh)
            decode_stages = 1  # unstaged (tensor axis, not stage axis)
        elif (cfg.max_batch > 1 or cfg.inference_dtype == "int8" or pchunk
              or cfg.prefix_cache > 0 or cfg.kv_pool_blocks > 0):
            # Continuous batching multiplexes concurrent requests onto
            # shared ragged batched decodes (runtime.batcher), riding the
            # staged DecodeEngine (single program per phase, ragged +
            # int8 + chunked-prefill support); int8, PREFILL_CHUNK, and
            # PREFIX_CACHE also need the engine (the per-device
            # PipelineRunner casts float dtypes but neither quantizes,
            # chunks its prefill, nor holds reusable KV state).
            # The PipelineRunner stays the plain single-stream path.
            from ..runtime.engine import DecodeEngine
            if cfg.kv_pool_blocks > 0:
                # paged KV storage gathers/scatters whole-model cache
                # rows, so the engine runs unstaged (per-stage cache
                # lists page in a later PR)
                runner = DecodeEngine(params, config, max_seq=cfg.max_seq,
                                      dtype=dtype)
                decode_stages = 1
            else:
                runner = DecodeEngine(params, config, max_seq=cfg.max_seq,
                                      boundaries=list(cfg.boundaries),
                                      dtype=dtype, prefill_chunk=pchunk)
        else:
            runner = PipelineRunner(params, config, list(cfg.boundaries),
                                    max_seq=cfg.max_seq, dtype=dtype)
        if switcher is not None:
            # continuous mode built its engine, pool, and certified
            # plan set above; admissions route through the switcher
            pass
        elif cfg.kv_pool_blocks > 0:
            # the paged KV block pool (runtime.kv_pool): one ref-counted
            # block store shared by the prefix store and whichever
            # decode front end serves /generate. An INJECTED pool
            # (graftfleet) is shared across replica apps — prefill
            # replicas fill its registry, decode replicas adopt the
            # blocks zero-copy; geometry is validated against this
            # app's engine below (PagedKVRunner / PrefixCachingEngine
            # constructors), same as an owned pool.
            if kv_pool is not None:
                eng_ = (spec_runner.plain if spec_runner is not None
                        else runner)
                if kv_pool.max_seq != eng_._cache_seq:
                    raise ValueError(
                        f"injected kv_pool spans {kv_pool.max_seq} "
                        f"slots, engine cache is {eng_._cache_seq} — "
                        "shared-pool replicas must agree on geometry")
                # storage regime is geometry too: a decode replica
                # gathering f32 views from a pool a prefill replica
                # filled as int8 (or vice versa) would be a silent
                # cross-replica numerics mismatch
                from ..utils.graftnum import regime_of as _regime_of
                want = (_regime_of(cfg.kv_pool_dtype)
                        if cfg.kv_pool_dtype else None)
                if kv_pool.block_dtype != want:
                    raise ValueError(
                        f"injected kv_pool stores {kv_pool.block_regime} "
                        f"blocks, KV_POOL_DTYPE={cfg.kv_pool_dtype!r} — "
                        "shared-pool replicas must agree on block "
                        "storage")
            else:
                from ..runtime.kv_pool import KVBlockPool
                kv_pool = KVBlockPool.for_engine(
                    spec_runner.plain if spec_runner is not None
                    else runner,
                    num_blocks=cfg.kv_pool_blocks,
                    block_size=cfg.kv_block_size,
                    block_dtype=cfg.kv_pool_dtype or None)
        elif kv_pool is not None:
            raise ValueError("kv_pool injected but KV_POOL_BLOCKS=0 — "
                             "a silently unused pool would misreport "
                             "the serving composition")
        if (kv_pool is not None and cfg.kv_host_blocks > 0
                and kv_pool.tier is None):
            # grafttier host spill tier (runtime.kv_tier): cold prefix
            # entries demote to bounded host RAM instead of LRU-evicting
            # to oblivion, and promote back on an affinity hit. An
            # injected pool may arrive with its tier already attached
            # (graftfleet replicas share the pool AND its tier).
            from ..runtime.kv_tier import HostKVTier
            kv_pool.attach_tier(HostKVTier(cfg.kv_host_blocks))
        prefix_runner = None
        if cfg.prefix_cache > 0:
            # cross-request KV reuse (runtime.prefix_cache): wraps the
            # plain single-stream engine built above; with SPEC_DECODE
            # also on, the verify loop decodes off the prefix-built
            # cache. With a KV pool, store entries hold ref-counted
            # block ids (structural sharing + LRU under pool pressure)
            # instead of full cache copies.
            from ..runtime.prefix_cache import PrefixCachingEngine
            prefix_runner = PrefixCachingEngine(
                runner, capacity=cfg.prefix_cache,
                chunk=cfg.prefix_chunk or cfg.prefill_chunk or 64,
                spec=spec_runner, pool=kv_pool)
            runner = prefix_runner
        if switcher is not None:
            pass   # the plan set IS the batching decision, per wave
        elif cfg.max_batch > 1:
            base = (prefix_runner.plain if prefix_runner is not None
                    else runner)
            if cfg.batch_mode == "iter":
                # iteration-level scheduling: requests join the live
                # batch at the next decode segment; early-EOS rows free
                # their slot (runtime.iterbatch; exclusions validated
                # above, so ``base`` here is always a DecodeEngine).
                # SPEC_DECODE batches advance by draft-verify segments;
                # PREFIX_CACHE backs admission prefills with the store;
                # KV_POOL_BLOCKS pages row state with watermark
                # admission and preemption/resume.
                from ..runtime.iterbatch import IterBatchingEngine
                runner = IterBatchingEngine(base,
                                            max_batch=cfg.max_batch,
                                            max_wait_ms=cfg.batch_wait_ms,
                                            spec=spec_runner,
                                            prefix=prefix_runner,
                                            pool=kv_pool,
                                            replica=replica_label)
            else:
                from ..runtime.batcher import BatchingEngine
                runner = BatchingEngine(base, max_batch=cfg.max_batch,
                                        max_wait_ms=cfg.batch_wait_ms,
                                        prefix=prefix_runner,
                                        spec=spec_runner)
        elif kv_pool is not None:
            # solo paged decode: the engine's own programs on
            # pool-backed storage; a prefix hit REFERENCES store blocks
            # instead of copying the prefill state
            from ..runtime.kv_pool import PagedKVRunner
            runner = PagedKVRunner(
                prefix_runner.plain if prefix_runner is not None
                else runner, kv_pool, prefix=prefix_runner)
    if not partitionable:
        compat_specs = compat_params = None
    else:
        compat_specs = P_.make_stage_specs(n_layer, [cfg.split_at])
        compat_params = {
            role: ((stage_only if stage_only is not None
                    else P_.extract_stage_params(params, compat_specs[i]))
                   if cfg.shard_role == role else None)
            for i, role in enumerate(("a", "b"))
        }

    app = JSONApp(title="llm-sharding-demo-tpu", version="0.1.0")

    @app.get("/metrics")
    def metrics():
        # Prometheus text exposition (the reference has no metrics at all,
        # SURVEY.md §5): request counters, gauges + latency histograms.
        return reg.prometheus()

    def _topology() -> dict:
        """The decode topology/composition ACTUALLY serving /generate —
        the single source for /healthz and the flight-recorder header
        (/debug/requests), so the two can never disagree."""
        topo = {
            "role": cfg.shard_role,
            "model": cfg.model_id,
            "n_stages": decode_stages,
            "dispatch": cfg.dispatch,
            "max_batch": cfg.max_batch,
            "batch_mode": cfg.batch_mode,
            "inference_dtype": cfg.inference_dtype,
            "spec_decode": cfg.spec_decode,
            "prefill_chunk": cfg.prefill_chunk,
            "prefix_cache": cfg.prefix_cache,
            "pp_decode": cfg.pp_decode,
            "ep_decode": cfg.ep_decode,
            "tp_decode": cfg.tp_decode,
            "kv_pool_blocks": cfg.kv_pool_blocks,
            "kv_block_size": cfg.kv_block_size,
            "kv_pool_dtype": cfg.kv_pool_dtype,
            "kv_host_blocks": cfg.kv_host_blocks,
            # graftfleet (llm_sharding_demo_tpu/fleet): this replica's
            # declared role and the prefix-store alignment width the
            # router's affinity keys must match
            "fleet_role": cfg.fleet_role,
            "prefix_chunk": cfg.prefix_chunk,
        }
        if switcher is not None:
            # continuous mode (graftwatch): auto_plan is LIVE, not
            # startup-only — the current plan, switch count, and wave
            # config, merged over any startup-planner row
            topo["auto_plan"] = {**(auto_plan_info or {}),
                                 **switcher.health_view()}
        elif auto_plan_info is not None:
            # how the knobs above were resolved (AUTO_PLAN=1): the
            # planner's chosen row, so monitoring can tell a planned
            # topology from a hand-tuned one
            topo["auto_plan"] = auto_plan_info
        return topo

    @app.get("/healthz")
    def healthz():
        live = {}
        from ..runtime.iterbatch import IterBatchingEngine as _IB
        if switcher is not None:
            # continuous mode: the pooled scheduler's stats stay
            # visible whichever plan is active (its worker lives for
            # the process; "active" rides the auto_plan block)
            for _r in switcher.plans.values():
                if isinstance(_r, _IB):
                    live["iter_batch_stats"] = _r.stats()
        elif isinstance(runner, _IB):
            # iteration-level scheduler: joins/segments/eos-retires
            # (spec_segments counts draft-verify segments when
            # SPEC_DECODE composes)
            live["iter_batch_stats"] = runner.stats()
            if runner.prefix is not None:
                live["prefix_cache_stats"] = runner.prefix.stats()
        else:
            # prefix cache: live hit/miss/entries — directly, or through
            # the batcher when PREFIX_CACHE composes with MAX_BATCH>1
            prefix_src = getattr(runner, "prefix", None)
            if prefix_src is None and hasattr(runner, "stats"):
                prefix_src = runner
            if prefix_src is not None and hasattr(prefix_src, "stats"):
                live["prefix_cache_stats"] = prefix_src.stats()
        if spec_runner is not None:  # speculation: live acceptance stats
            live["spec_decode_stats"] = spec_runner.stats()
        if kv_pool is not None:  # paged KV memory: allocator truth
            st = kv_pool.stats()
            # Pool-stats conservation invariant (graftsan satellite):
            # every block is free or referenced, never both or neither.
            # Drift here means the allocator's accounting broke — turn
            # it into a 500 (the handler's uncaught-exception path)
            # instead of serving a silently wrong gauge.
            if st["blocks_in_use"] + st["blocks_free"] != st["blocks_total"]:
                raise AssertionError(
                    "kv_pool_stats conservation violated: "
                    f"{st['blocks_in_use']} in_use + {st['blocks_free']} "
                    f"free != {st['blocks_total']} total")
            # HBM bytes of the pool's device planes, from the graftmem
            # ledger (codes + quantized scales) — NEVER re-derived from
            # shape arithmetic here, so byte reporting has exactly one
            # bookkeeping path (the blocks-conservation discipline,
            # applied to bytes)
            st["pool_bytes"] = (
                graftmem.holding_bytes(kv_pool, "data")
                + graftmem.holding_bytes(kv_pool, "scales"))
            if kv_pool.tier is not None:
                # Per-tier conservation (the grafttier analog of the
                # block assert above): entries, occupancy, and the
                # movement ledger must agree, and the tier block's
                # measured host_bytes is the graftmem host_spill
                # component's own bookkeeping (holding_bytes) — drift
                # turns the health check red, not a silently wrong
                # capacity report.
                kv_pool.tier.graftsan_check("healthz")
            live["kv_pool_stats"] = st
        # Byte-conservation invariant (the blocks_in_use + blocks_free
        # == blocks_total pattern, applied to the HBM ledger): the
        # per-entry table must agree with the running component/grand
        # totals. Drift means the ledger's accounting broke — 500, not
        # a silently wrong /debug/memory.
        mem = graftmem.snapshot()
        if graftmem.enabled() and not mem["conserved"]:
            raise AssertionError(
                "graftmem byte conservation violated: component sum "
                f"{mem['components']} disagrees with ledger total "
                f"{mem['total_bytes']}")
        # Live placement auditor (utils/graftshard, GRAFTSHARD=1):
        # armed/checks/violations/tracked, so operators can see whether
        # placement discipline is being enforced — and a violation that
        # slipped past the raise path (audit-only drift) turns the
        # health check red instead of hiding in a log.
        shard_status = graftshard.status()
        if shard_status["enabled"]:
            shard_status["audit"] = graftshard.audit()
            if shard_status["audit"]:
                raise AssertionError(
                    "graftshard placement contract violated: "
                    f"{shard_status['audit']}")
        return {
            **live,
            "status": "ok",
            "graftshard": shard_status,
            # trend-watch state (utils/grafttrend): declared watch
            # count, evaluation count, and any LATCHED trips — a page
            # that fired is visible on the health probe, not only on
            # the debug surface
            "trend": trend_reducer.health_view(),
            **_topology(),
            "devices": [str(d) for d in jax.devices()],
        }

    @app.get("/debug/requests")
    def debug_requests(query: dict):
        """Flight recorder: JSON span timelines of the last N completed
        /generate requests (bounded ring — see utils.tracing.
        FlightRecorder). ``?n=K`` caps the rows returned, ``?slowest=1``
        orders by duration instead of recency — the view that answers
        "where did that slow request's time go" without a profiler —
        and ``?errors=1`` keeps only failed requests (error-labeled
        traces: timeouts, shed 429s, typed 503s, upstream failures),
        the fault-triage view graftfault's degraded paths feed.
        ``?profile=<label>`` keeps only requests carrying that
        X-Workload-Profile label — the view that triages ONE graftload
        workload profile's slow/failed requests out of a mixed run
        (composes with ``errors``/``slowest``)."""
        return tracing.debug_requests_payload(rec, query, _topology())

    @app.get("/debug/profile")
    def debug_profile(query: dict):
        """graftscope attribution view (utils/graftscope): bounded
        per-program dispatch-timing rings for every PROFILED_SCOPES jit
        entry point plus the occupancy time series (pool blocks in use,
        batch occupancy, queue depth). ``?n=K`` caps ring samples and
        series points per entry. Honesty header rides the payload: the
        dispatch numbers are serving-thread enqueue windows unless sync
        mode is armed (never in serving) — device-level truth is the
        profiler trace's job, exactly as utils/tracing documents."""
        try:
            n = int(query.get("n", "32"))
        except ValueError:
            return 422, {"detail": "n must be an integer"}
        from ..utils import graftscope
        return {
            "serving": _topology(),
            **graftscope.snapshot(n=n),
        }

    @app.get("/debug/plan")
    def debug_plan(query: dict):
        """Continuous-planning decision state (utils/graftwatch): the
        active plan, per-plan scores under the live windowed estimate,
        calibrated byte weights, each plan's certified program cost,
        the bounded switch-event journal (``?n=K`` caps events), and
        the declared PLAN_SIGNALS provenance map with live signal
        values. Off continuous mode the payload still answers (mode
        "startup"/"off") so monitoring can tell WHY there is no switch
        history instead of reading a 404."""
        if switcher is None:
            return {
                "serving": _topology(),
                "mode": "startup" if auto_plan_info is not None
                else "off",
                "auto_plan": auto_plan_info,
            }
        try:
            n = int(query.get("n", "16"))
        except ValueError:
            return 422, {"detail": "n must be an integer"}
        return {"serving": _topology(), **switcher.describe(n=n)}

    @app.get("/debug/memory")
    def debug_memory():
        """graftmem HBM ledger view (utils/graftmem): the per-component
        live-byte table with peaks and per-device attribution, the
        hottest registered holdings, the conservation verdict, and —
        when a pool serves — the pool geometry with its ledger-derived
        ``pool_bytes``. Bytes are live jax buffer nbytes over
        REGISTERED holdings (the MEMORY_LEDGER contract; the payload's
        honesty header spells what is and is not counted). Same
        topology header as /healthz (pinned equal by tests)."""
        body = {
            "serving": _topology(),
            **graftmem.snapshot(),
        }
        if kv_pool is not None:
            st = kv_pool.stats()
            st["pool_bytes"] = (
                graftmem.holding_bytes(kv_pool, "data")
                + graftmem.holding_bytes(kv_pool, "scales"))
            body["pool"] = st
        return body

    @app.get("/debug")
    def debug_index():
        """The debug-surface index: every /debug/* endpoint with a
        one-line description, under the SAME topology header as
        /healthz (pinned equal by tests) — operators stop guessing
        URLs and stop wondering which composition a surface reflects."""
        return {
            "serving": _topology(),
            "surfaces": {
                "/debug/requests": (
                    "flight recorder: span trees of the last N "
                    "requests (?n, ?slowest=1, ?errors=1, ?profile=)"),
                "/debug/profile": (
                    "graftscope attribution: per-program dispatch "
                    "rings + occupancy time series (?n)"),
                "/debug/plan": (
                    "graftwatch continuous-planning decision state: "
                    "active plan, scores, switch journal (?n)"),
                "/debug/timeline": (
                    "grafttime unified causal event stream, one clock "
                    "over spans/dispatches/faults/plan switches "
                    "(?rid=, ?since=, ?since_seq=, ?kinds=, ?n=)"),
                "/debug/trend": (
                    "grafttrend watch state: declared WATCH_POLICY "
                    "verdicts, windowed series reductions, alert "
                    "journal, refit history (?eval=0 reads without "
                    "polling/evaluating)"),
                "/debug/memory": (
                    "graftmem HBM ledger: per-component live bytes, "
                    "peaks, per-device attribution, pool geometry, "
                    "byte-conservation verdict"),
            },
        }

    @app.get("/debug/timeline")
    def debug_timeline(query: dict):
        """The unified causal timeline (utils/grafttime): every
        producer's typed events on one monotonic clock. ``?rid=``
        keeps one request's causal stream (shared batched phases
        included — they carry the rid set), ``?since=`` is an
        exclusive ms lower bound on the bus clock, ``?kinds=`` a
        comma-separated vocabulary filter, ``?n=`` caps to the newest
        n. Export the payload with ``python -m tools.grafttime
        export`` for chrome://tracing / Perfetto."""
        return grafttime.debug_timeline_payload(query, _topology())

    @app.get("/debug/trend")
    def debug_trend(query: dict):
        """Trend & drift watch state (utils/grafttrend): per-watch
        verdicts against the declared WATCH_POLICY, windowed series
        reductions (rate, p50/p99 sketch), the bounded alert journal,
        and the refit history. The default GET is the poll-on-read
        loop: it taps the live producers (registry histogram buckets,
        counters, gauges) and EVALUATES the watches — scraping this
        surface is the alerting cadence (trips latch, so repeated
        scrapes of a sustained burn alert once). ``?eval=0`` reads
        the current state without polling or evaluating."""
        if query.get("eval", "1") != "0":
            trend_reducer.poll()
            trend_reducer.evaluate()
        return {"serving": _topology(), **trend_reducer.describe()}

    @app.post("/prefill")
    def prefill(req: PrefillReq, headers: dict):
        # thin wrapper: the replica label rides every timeline event
        # this request emits (grafttime's ambient replica correlator)
        with grafttime.use_replica(replica_label):
            return _prefill(req, headers)

    def _prefill(req: PrefillReq, headers: dict):
        """graftfleet prefill-replica endpoint: run the prompt's
        chunk-aligned prefill and FILL shared pool blocks — the walk
        lands every full-chunk prefix state in the pool's content-keyed
        registry (``register_prefix``, the registry holding its own
        refs), where decode replicas adopt it zero-copy via
        ``prefill_shared``. Nothing but the prompt crosses the hop and
        nothing but block ids change hands afterward: transfer is
        block handoff, never a tensor copy (fleet/topology.py
        HANDOFF_POLICY documents the lifetime rule). Typed sheds ride
        the same paths as /generate: pool saturation answers 429 +
        Retry-After, an exhausted X-Deadline-Ms budget 503."""
        rid, _profile = parse_request_identity(headers)
        hdrs = {"X-Request-ID": rid}

        def out(body, status=200):
            return status, body, hdrs

        if cfg.fleet_role != "prefill":
            return out({"error": "This instance is not a fleet "
                                 "prefill replica."}, status=400)
        # the FLEET_ROLE guard in utils.config makes this unreachable
        # (prefill requires the pool-backed store); belt and braces for
        # injected-model tests that bypass from_env
        if prefix_runner is None or kv_pool is None:
            return out({"error": "prefill replicas need the pool-backed "
                                 "prefix store (KV_POOL_BLOCKS + "
                                 "PREFIX_CACHE)"}, status=400)
        deadline, _dl_ms, dl_err = parse_deadline_header(headers)
        if dl_err:
            return out({"error": dl_err}, status=400)
        trace = tracing.RequestTrace(rid, fleet="prefill")

        def reject(msg: str):
            # a proper 400, flight-recorded: /prefill is a new
            # non-parity endpoint, and the router keys its degraded-
            # warm accounting on the status code — a 200-with-error
            # body would count as a successful warm
            trace.labels.update(error=msg)
            rec.record(trace)
            return out({"error": msg}, status=400)

        with trace.span("tokenize"):
            prompt_ids = tokenizer.encode(req.prompt)
        if not prompt_ids:
            return reject("prompt tokenized to zero tokens")
        if len(prompt_ids) >= cfg.max_seq:
            return reject(f"prompt ({len(prompt_ids)} tokens) leaves "
                          f"no forward room under max_seq "
                          f"({cfg.max_seq})")
        chunk = prefix_runner.chunk
        m_total = (len(prompt_ids) - 1) // chunk
        alloc = kv_pool.allocator
        # admission: a registry fill the pool cannot host is SHED, not
        # queued — the 429 + Retry-After discipline every fleet hop
        # shares (the walk itself also degrades gracefully on a full
        # pool, skipping the insert; this gate sheds before paying the
        # prefill compute)
        need = alloc.blocks_for(m_total * chunk)
        if need:
            # registered prefixes SHARE blocks (_insert_pool): a warm
            # repeat fill allocates nothing, and a partial hit only the
            # new chunks' blocks — gate on that marginal need, or warm
            # prefills (the replica's whole point) get shed whenever
            # the pool is busy. has_prefix takes no leases: this walk
            # is the same key ladder _lookup descends, refs deferred to
            # the walk itself.
            arr = np.asarray(prompt_ids, dtype=np.int32)
            key_of = prefix_runner._key
            if alloc.has_prefix(key_of(arr, m_total, chunk)):
                need = 0
            else:
                for m in range(m_total - 1, 0, -1):
                    if alloc.has_prefix(key_of(arr, m, chunk)):
                        need -= (m * chunk) // kv_pool.block_size
                        break
        if need > 0 and alloc.available() < need:
            reg.inc("kv_pool_admission_rejections_total")
            hdrs["Retry-After"] = "1"
            trace.labels.update(error="kv_pool_saturated")
            rec.record(trace)
            return out({"error": "kv_pool_saturated",
                        "detail": "pool cannot host this prefix fill; "
                                  "retry after the indicated backoff"},
                       status=429)
        try:
            if deadline is not None:
                deadline.raise_if_expired("prefill")
            with tracing.use_trace(trace):
                _logits, _cache, shared_ids, depth = \
                    prefix_runner.prefill_shared(
                        np.asarray(prompt_ids, dtype=np.int32))
            # the walk's caller refs are released immediately: the
            # REGISTRY holds the entry's own refs, and this endpoint
            # hands off ids by content key, never by lease
            alloc.free(shared_ids)
        except graftfault.Unavailable as e:
            hdrs["Retry-After"] = str(max(1, int(round(e.retry_after))))
            if e.code == "deadline_exceeded":
                reg.inc("deadline_misses_total")
            trace.labels.update(error=e.code)
            rec.record(trace)
            # post-mortem black box (grafttime): the events that led
            # to the typed failure, journaled before the ring rotates
            grafttime.blackbox(e.code, rid=rid)
            return out({"error": e.code, "detail": str(e)}, status=503)
        except Exception as e:  # noqa: BLE001 — flight-record + echo id
            trace.labels.update(error=f"{type(e).__name__}: {e}")
            rec.record(trace)
            from ..runtime.kv_pool import GraftsanError
            if isinstance(e, GraftsanError):
                grafttime.blackbox(f"graftsan:{type(e).__name__}",
                                   rid=rid)
            return out({"detail": f"{type(e).__name__}: {e}"}, status=500)
        trace.labels.update(registered_tokens=depth)
        rec.record(trace)
        return out({"registered_tokens": depth,
                    "prefix_entries": alloc.prefix_len(),
                    "chunk": chunk})

    @app.post("/forward")
    def forward_a(req: InputIDs):
        if cfg.shard_role != "a":
            return {"error": "This instance is not shard A."}
        if not partitionable:
            return {"error": "stage endpoints serve dense GPT-2 only; "
                             f"{type(config).__name__} models generate "
                             "via /generate"}
        ids = jnp.asarray([req.input_ids], dtype=jnp.int32)
        hidden, _ = P_.stage_apply(compat_params["a"], compat_specs[0],
                                   config, ids)
        return {"hidden_states": np.asarray(hidden).tolist()}

    @app.post("/forward_b")
    def forward_b(req: HiddenStates):
        if cfg.shard_role != "b":
            return {"error": "This instance is not shard B."}
        if not partitionable:
            return {"error": "stage endpoints serve dense GPT-2 only; "
                             f"{type(config).__name__} models generate "
                             "via /generate"}
        hidden = jnp.asarray(np.asarray(req.hidden_states, dtype=np.float32))
        logits, _ = P_.stage_apply(compat_params["b"], compat_specs[1],
                                   config, hidden)
        return {"logits": np.asarray(logits).tolist()}

    def _generate_local(req: GenerateReq, prompt_ids: List[int],
                        eos_id: Optional[int] = None,
                        deadline: Optional[graftfault.Deadline] = None,
                        ) -> List[int]:
        sampling = (SamplingConfig(mode="greedy") if req.mode == "greedy"
                    else SamplingConfig(mode="sample",
                                        temperature=req.temperature,
                                        top_k=req.top_k,
                                        top_p=req.top_p))
        seed = req.seed if req.seed is not None else int(
            np.random.default_rng().integers(2 ** 31))
        # Speculation serves only the requests it is exact and safe for:
        # prompt at least ngram long and draft_len slots of cache headroom
        # left (greedy is token-exact, sample distribution-exact via
        # rejection sampling). Everything else uses the plain engine —
        # same weights, just one token per forward. With PREFIX_CACHE on
        # (solo), the prefix engine IS the entry point and applies the
        # same spec eligibility internally (runtime.prefix_cache).
        # Behind a batching front end (MAX_BATCH>1), routing is the
        # ``SamplingConfig.spec`` flag: flagged requests gather into
        # spec-only rounds/batches (policy equality keeps FIFO) and
        # decode through the batched verify loop.
        eng = runner
        plan_release = None
        if switcher is not None:
            # continuous mode: ONE admission observation per request,
            # wave-boundary re-planning inside admit(), and the plan
            # that serves THIS request returned — in-flight requests
            # keep the runner they were admitted to across a switch
            # (both front ends share every compiled program and the
            # one block pool, so nothing leaks and nothing recompiles)
            eng, plan_label = switcher.admit(len(prompt_ids),
                                             req.max_new_tokens)
            plan_release = switcher.release
            tr = tracing.current_trace()
            if tr is not None:
                tr.labels.update(plan=plan_label)
        # the try/finally opens HERE, not at the generate call: anything
        # below can raise (the deadline pre-check especially — expired
        # budgets are routine under the abandonment profile), and a
        # skipped release would leak the watcher's in-flight estimate
        # permanently, biasing every later plan decision wide
        try:
            import dataclasses as _dc

            from ..runtime.batcher import BatchingEngine as _BE
            from ..runtime.engine import DecodeEngine as _DE
            from ..runtime.iterbatch import IterBatchingEngine as _IB
            eligible = (spec_runner is not None
                        and spec_runner.eligible(len(prompt_ids),
                                                 req.max_new_tokens))
            if eligible and isinstance(runner, (_BE, _IB)):
                sampling = _dc.replace(sampling, spec=True)
            elif eligible and cfg.prefix_cache == 0:
                eng = spec_runner
            from ..runtime.kv_pool import PagedKVRunner as _PR
            kw = {}
            if eos_id is not None and isinstance(eng, (_DE, _IB, _PR)):
                # segment-boundary early exit: stop_at_eos requests stop
                # paying device time for dead tokens past the stop
                # (tokens emitted are the exact prefix of the uncapped
                # stream; the iter scheduler additionally frees the
                # row's slot). Other runners (spec/prefix/admission-
                # batcher/pipeline) keep the host-side truncation below
                # — same wire result.
                kw["eos_id"] = eos_id
            if deadline is not None:
                # the deadline budget is honored END-TO-END on the iter
                # scheduler (queue wait, segment-boundary cancellation
                # with blocks freed) and per-hop on remote dispatch;
                # other runners at least refuse work the budget cannot
                # cover
                deadline.raise_if_expired("generate")
                if isinstance(eng, _IB):
                    kw["deadline"] = deadline
            result = eng.generate(np.asarray(prompt_ids),
                                  max_new_tokens=req.max_new_tokens,
                                  sampling=sampling,
                                  key=jax.random.PRNGKey(seed), **kw)
        finally:
            if plan_release is not None:
                plan_release()   # the watcher's in-flight estimate
        # row_tokens strips any left pad the engine introduced (chunked
        # prefill alignment); plain runs return the row unchanged
        return [int(t) for t in result.row_tokens(0)]

    # One hop discipline for every coordinator->shard POST
    # (utils/graftfault.HopPolicy): capped exponential backoff + seeded
    # jitter between attempts, a per-request retry budget, and a
    # per-shard circuit breaker — a dead shard fails fast with a typed
    # 503 + Retry-After instead of stacking 30s timeouts. Each retry is
    # counted into shard_hop_retries_total{stage,reason}. UpstreamError
    # (an error BODY from a live shard — misroute, missing key) is
    # fatal: repetition does not fix routing.
    hop_policy = graftfault.HopPolicy(
        attempts=3, timeout_s=30.0, base_backoff_s=0.25,
        max_backoff_s=2.0, breaker_threshold=5, breaker_cooldown_s=5.0,
        fatal=(UpstreamError,), registry=reg,
        on_retry=lambda shard, reason: reg.inc(
            "shard_hop_retries_total", stage=shard, reason=reason))

    def _relay(shard: str, url: str, payload: dict, key: str,
               deadline: Optional[graftfault.Deadline] = None):
        """One shard hop through the typed HopPolicy.

        Failure modes the reference leaves raw (SURVEY.md §2.3.5: its
        role-guard 200s make raise_for_status useless and a misroute
        dies as a KeyError): connection errors/timeouts (retried under
        the policy's capped backoff, per-attempt timeout derived from
        the remaining deadline budget), HTTP errors, and
        200-with-``{"error"}`` bodies. Transport failures surface as
        UpstreamError -> a typed 502; an open breaker or an exhausted
        deadline surfaces as graftfault.Unavailable -> a typed 503 +
        Retry-After. Seeded fault injection (GRAFTFAULT) lands HERE,
        before the wire call, so the whole retry/breaker path replays
        deterministically.
        """
        import requests

        def attempt(timeout_s: float):
            kind = graftfault.inject("serving.shard_hop", "reset",
                                     "timeout", "http_503", "slow")
            if kind == "reset":
                raise requests.exceptions.ConnectionError(
                    "graftfault: injected connection reset")
            if kind == "timeout":
                raise requests.exceptions.Timeout(
                    "graftfault: injected hop timeout")
            if kind == "http_503":
                raise requests.exceptions.HTTPError(
                    "graftfault: injected shard 503")
            if kind == "slow":
                import time as _time
                _time.sleep(min(0.05, timeout_s))
            resp = requests.post(url, json=payload, timeout=timeout_s)
            resp.raise_for_status()
            body = resp.json()
            if key not in body:
                raise UpstreamError(
                    shard, url,
                    str(body.get("error", f"response missing {key!r}")))
            return body[key]

        try:
            return hop_policy.call(attempt, shard=shard,
                                   deadline=deadline)
        except (UpstreamError, graftfault.Unavailable):
            raise
        except requests.exceptions.RequestException as e:
            raise UpstreamError(shard, url, f"{type(e).__name__}: {e}")

    def _generate_remote(req: GenerateReq, prompt_ids: List[int],
                         eos_id: Optional[int] = None,
                         deadline: Optional[graftfault.Deadline] = None,
                         ) -> List[int]:
        """Reference-topology decode: per token, POST the full sequence to
        shard A, relay hidden states to shard B, sample host-side
        (reference server.py:169-206). O(n²) and JSON-lossy by design —
        it exists for wire-level drop-in compatibility, not speed.

        Sampling goes through ``engine.sampler_pmf`` — THE sampler
        definition — with a host-side ``rng.choice`` draw (seed contract:
        one numpy draw per token, as before). Unlike the fixed-length
        device scan, this Python loop CAN stop at EOS, saving the
        remaining per-token HTTP round trips."""
        from ..runtime.engine import sampler_pmf
        ids = list(prompt_ids)
        rng = np.random.default_rng(req.seed)
        sampling = (None if req.mode == "greedy" else
                    SamplingConfig(mode="sample",
                                   temperature=req.temperature,
                                   top_k=req.top_k, top_p=req.top_p))
        for _ in range(req.max_new_tokens):
            hidden = _relay("a", f"{cfg.shard_a_url}/forward",
                            {"input_ids": ids}, "hidden_states",
                            deadline=deadline)
            logits = np.asarray(_relay(
                "b", f"{cfg.shard_b_url}/forward_b",
                {"hidden_states": hidden}, "logits",
                deadline=deadline))[0, -1]
            if req.mode == "greedy":
                ids.append(int(np.argmax(logits)))
            else:
                probs, top_idx = sampler_pmf(jnp.asarray(logits), sampling)
                probs = np.asarray(probs, dtype=np.float64)
                ids.append(int(rng.choice(np.asarray(top_idx),
                                          p=probs / probs.sum())))
            if eos_id is not None and ids[-1] == eos_id:
                break
        return ids

    @app.post("/generate")
    def generate(req: GenerateReq, headers: dict):
        # thin wrapper: the replica label rides every timeline event
        # this request emits (grafttime's ambient replica correlator)
        with grafttime.use_replica(replica_label):
            return _generate(req, headers)

    def _generate(req: GenerateReq, headers: dict):
        # Request identity: every response (errors included) echoes the
        # X-Request-ID as a response header — the BODY stays wire-parity
        # with the reference ({"generated": ...}, server.py:210). The
        # X-Workload-Profile label (graftload) lets the flight recorder
        # filter per traffic shape (/debug/requests?profile=...).
        rid, profile_label = parse_request_identity(headers)
        hdrs = {"X-Request-ID": rid}

        def out(body, status=200):
            return status, body, hdrs

        if cfg.shard_role != "coordinator":
            return out({"error": "This instance is not coordinator."})
        if req.max_new_tokens < 1:
            return out({"error": "max_new_tokens must be >= 1"})
        # Per-request deadline budget (graftfault): ``X-Deadline-Ms``
        # caps the caller's total wait — HTTP wait, queue wait, shard
        # hop timeouts, and in-flight decode all derive from the
        # remaining budget; a row past its deadline is cancelled at the
        # next segment boundary with its blocks freed, and the caller
        # gets a typed 503 + Retry-After instead of a hung connection.
        deadline, dl_ms, dl_err = parse_deadline_header(headers)
        if dl_err:
            return out({"error": dl_err}, status=400)
        trace = tracing.RequestTrace(rid, mode=req.mode,
                                     dispatch=cfg.dispatch)
        if profile_label is not None:
            trace.labels.update(profile=profile_label)
        if deadline is not None:
            trace.labels.update(deadline_ms=dl_ms)
        with trace.span("tokenize"):
            prompt_ids = tokenizer.encode(req.prompt)
        if not prompt_ids:
            return out({"error": "prompt tokenized to zero tokens"})
        if len(prompt_ids) + req.max_new_tokens > cfg.max_seq:
            return out({"error": f"prompt ({len(prompt_ids)} tokens) + "
                        f"max_new_tokens ({req.max_new_tokens}) exceeds "
                        f"max_seq ({cfg.max_seq})"})
        if req.mode not in ("sample", "greedy"):
            return out({"error": f"unknown mode {req.mode!r}"})
        if req.mode == "sample":
            if req.temperature <= 0:
                return out({"error": "temperature must be > 0"})
            if not 1 <= req.top_k <= config.vocab_size:
                return out(
                    {"error": f"top_k must be in [1, {config.vocab_size}]"})
            if not 0.0 < req.top_p <= 1.0:
                return out({"error": "top_p must be in (0, 1]"})
        eos_id = None
        if req.stop_at_eos or req.eos_token_id is not None:
            eos_id = (req.eos_token_id if req.eos_token_id is not None
                      else getattr(tokenizer, "eos_token_id", None))
            if eos_id is None:
                return out({"error": "stop_at_eos requested but the "
                            "tokenizer has no eos_token_id; pass "
                            "eos_token_id explicitly"})
            if not 0 <= eos_id < config.vocab_size:
                return out(
                    {"error": f"eos_token_id {eos_id} out of vocab range"})
        if kv_pool is not None and cfg.dispatch == "local":
            # Admission control (runtime.kv_pool): a request the KV
            # pool cannot host — with the waiting line already at its
            # limit — is SHED with 429 + Retry-After instead of queued
            # unboundedly (the pre-pool behavior let the queue grow
            # without bound under sustained overload, trading it for
            # timeout storms). The iter scheduler owns the policy;
            # the solo paged runner rejects only what the pool could
            # never host right now.
            from ..runtime.iterbatch import IterBatchingEngine as _IB2
            # continuous mode gates against the ACTIVE plan (advisory,
            # like every admission answer here: the worker's actual
            # grant is the atomic admit_alloc path, so a wave switch
            # between this gate and dispatch costs one queue beat,
            # never a wrong failure)
            gate_runner = runner if switcher is None else switcher.peek()
            if isinstance(gate_runner, _IB2):
                ok, retry = gate_runner.admission_load(
                    len(prompt_ids), req.max_new_tokens)
            else:
                need = kv_pool.allocator.blocks_for(
                    len(prompt_ids) + req.max_new_tokens)
                # seeded pool-exhaustion spike (graftfault): the solo
                # paged runner's 429 gate sheds exactly as a full pool
                # would — the fleet router's per-replica shed/fallback
                # math is testable deterministically (the pooled iter
                # scheduler has the same site in admission_load)
                spike = graftfault.inject("serving.admission",
                                          "pool_spike")
                ok = (spike is None
                      and kv_pool.allocator.available() >= need)
                retry = 1.0
            if not ok:
                reg.inc("kv_pool_admission_rejections_total")
                hdrs["Retry-After"] = str(max(1, int(round(retry))))
                trace.labels.update(error="kv_pool_saturated")
                rec.record(trace)
                return out({"error": "kv_pool_saturated",
                            "detail": "KV memory pool cannot admit this "
                                      "request; retry after the "
                                      "indicated backoff"}, status=429)
        # The ambient trace rides the generation: solo runners record
        # prefill/decode spans directly; the batch schedulers capture it
        # onto their queue entry and stamp queue wait + shared phases
        # from the worker side (runtime.batcher / runtime.iterbatch).
        try:
            with timed("generate_request_seconds", registry=reg,
                       mode=req.mode, dispatch=cfg.dispatch):
                if cfg.dispatch == "remote":
                    try:
                        with tracing.use_trace(trace):
                            ids = _generate_remote(req, prompt_ids,
                                                   eos_id=eos_id,
                                                   deadline=deadline)
                    except UpstreamError as e:
                        # typed upstream failure (the reference propagates
                        # a raw exception -> opaque 500, server.py:173-180)
                        log.warning("upstream failure: %s", e)
                        reg.inc("upstream_failures_total", shard=e.shard)
                        trace.labels.update(error="upstream_failure",
                                            shard=e.shard)
                        rec.record(trace)
                        return out({"error": "upstream_failure",
                                    "shard": e.shard, "upstream": e.url,
                                    "detail": e.detail}, status=502)
                else:
                    with tracing.use_trace(trace):
                        ids = _generate_local(req, prompt_ids,
                                              eos_id=eos_id,
                                              deadline=deadline)
            # the response-assembly tail (EOS truncation, detokenize,
            # latency derivation) stays INSIDE the try: a decode error
            # surfacing there must still flight-record and echo the id
            finish_reason = "length"
            # tokens actually DECODED — captured before the host-side
            # EOS truncation below, so TPOT divides decode wall time by
            # the steps the device really ran, not the kept prefix (an
            # early EOS would otherwise inflate TPOT ~budget/kept-fold)
            n_decoded = len(ids) - len(prompt_ids)
            if eos_id is not None:
                # truncate at the first EOS among the NEW tokens (the
                # decode scan is fixed-length on device; stopping is a
                # host-side truncation, the standard serving semantics)
                new = ids[len(prompt_ids):]
                if eos_id in new:
                    ids = ids[:len(prompt_ids) + new.index(eos_id)]
                    finish_reason = "stop"
            n_new = len(ids) - len(prompt_ids)
            reg.inc("generate_requests_total", mode=req.mode)
            reg.inc("generated_tokens_total", value=n_new)
            log.info('{"event": "generate", "mode": "%s", '
                     '"request_id": "%s", "prompt_tokens": %d, '
                     '"new_tokens": %d, "finish_reason": "%s"}', req.mode,
                     rid, len(prompt_ids), n_new, finish_reason)
            with trace.span("detokenize"):
                try:
                    text = tokenizer.decode(ids, skip_special_tokens=True)
                except TypeError:  # ByteTokenizer takes no HF kwargs
                    text = tokenizer.decode(ids)
            trace.finish()
            # Latency split derived from the span tree. TTFT counts from
            # request arrival THROUGH the prefill (queue wait included —
            # what the caller experiences); runners without span
            # instrumentation (PipelineRunner, remote dispatch) fall
            # back to the whole request. TPOT divides the decode spans'
            # wall time over the inter-token steps actually decoded.
            pre = trace.find("prefill")
            ttft = (pre.t1 - trace.t0) if pre is not None \
                else trace.duration
            reg.observe("ttft_seconds", ttft, mode=req.mode)
            if n_decoded > 1:
                decode_spans = trace.find_all("decode")
                decode_wall = sum(s.duration for s in decode_spans)
                if not decode_spans:
                    decode_wall = max(trace.duration - ttft, 0.0)
                reg.observe("tpot_seconds", decode_wall / (n_decoded - 1),
                            mode=req.mode)
            trace.labels.update(prompt_tokens=len(prompt_ids),
                                new_tokens=n_new,
                                finish_reason=finish_reason,
                                ttft_ms=round(ttft * 1e3, 3))
            rec.record(trace)
        except graftfault.Unavailable as e:
            # typed degraded-mode unavailability (graftfault): deadline
            # budget exhausted, per-shard breaker open, transient-fault
            # park budget exhausted, or a permanent engine fault — 503 +
            # Retry-After with the partial span tree flight-recorded and
            # the X-Request-ID echoed, never an opaque 500
            hdrs["Retry-After"] = str(max(1, int(round(e.retry_after))))
            if e.code == "deadline_exceeded":
                # the SLO deadline_miss source series (loadgen
                # SLO_SOURCE_METRICS; the graftcheck slo pass verifies
                # this emission exists): accepted work that died on its
                # budget — distinct from the shed counters above
                reg.inc("deadline_misses_total")
            trace.labels.update(error=e.code)
            rec.record(trace)
            # post-mortem black box (grafttime): a typed Unavailable is
            # exactly the moment the causal stream must outlive the
            # ring — journal it (bounded; $GRAFTTIME_DIR adds a file)
            grafttime.blackbox(e.code, rid=rid)
            return out({"error": e.code, "detail": str(e)}, status=503)
        except Exception as e:  # noqa: BLE001 — a failed (e.g. timed-out)
            # generation is exactly the request the flight recorder must
            # keep, and the caller still needs its X-Request-ID echo;
            # body shape matches http.py's uncaught-500 {"detail": ...}
            trace.labels.update(error=f"{type(e).__name__}: {e}")
            rec.record(trace)
            from ..runtime.kv_pool import GraftsanError
            if isinstance(e, GraftsanError):
                # a sanitizer trap firing on the serving path is THE
                # black-box case: provenance + the event stream that
                # led to it, journaled at the instant it surfaced
                grafttime.blackbox(f"graftsan:{type(e).__name__}",
                                   rid=rid)
            return out({"detail": f"{type(e).__name__}: {e}"}, status=500)
        body = {"generated": text}
        if eos_id is not None:
            # extension field, absent in parity mode so the reference's
            # wire shape ({"generated": ...}, server.py:210) is untouched
            body["finish_reason"] = finish_reason
        return out(body)

    # continuous mode's decision state, exposed for the in-suite pins
    # (tests reach the certified plan set and the event journal through
    # the app object; the wire surface is GET /debug/plan)
    app.plan_switcher = switcher
    app.trend_reducer = trend_reducer
    return app


# Lazy module attribute so `from ...serving.app import app` builds the
# env-configured app on first access (the reference builds its app at
# import, server.py:129), while importing create_app for tests stays free.
# Cached: repeated access must not re-load the model.
def __getattr__(name: str):
    if name == "app":
        globals()["app"] = create_app()
        return globals()["app"]
    raise AttributeError(name)
