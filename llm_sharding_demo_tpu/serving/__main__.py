"""Process entry point: ``python -m llm_sharding_demo_tpu.serving``.

Replaces the reference's ``uvicorn server:app --host 0.0.0.0 --port 5000``
(reference Dockerfile:19); the port comes from ``SHARD_PORT`` (same env
contract, reference server.py:25) or ``--port``.
"""

from __future__ import annotations

import argparse
import logging

from .app import create_app
from .http import serve
from ..utils.config import from_env


def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=None,
                        help="default: SHARD_PORT env (5000)")
    args = parser.parse_args()
    cfg = from_env()
    app = create_app(cfg)  # create_app joins the multi-host runtime
    port = args.port if args.port is not None else cfg.shard_port
    logging.getLogger(__name__).info(
        "serving role=%s dispatch=%s on %s:%d",
        cfg.shard_role, cfg.dispatch, args.host, port)
    serve(app, host=args.host, port=port)


if __name__ == "__main__":
    main()
