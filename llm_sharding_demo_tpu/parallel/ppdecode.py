"""Single-program pipelined inference: shard_map + ppermute cached decode.

The inference sibling of ``parallel.gpipe`` and the endgame of
``parallel.pipeline``'s docstring: where ``PipelineRunner`` drives each
token with ``n_stages`` host dispatches plus ``n_stages - 1`` transfers
(the TPU translation of the reference's per-token HTTP hops, reference
server.py:169-181), here the ENTIRE generation is two compiled programs —
one pipelined prefill and one ``lax.scan`` over all decode steps. Per
token, host work is zero; the token crosses the stage ring inside the
program via ``lax.ppermute`` over ICI.

Layout (mesh axis ``pp``, size = n_stages):

- transformer blocks stage-major ``[n_stages, per_stage, ...]`` sharded
  ``P("pp")`` — each device owns exactly its stage's weights
  (``partition.stack_stage_params``);
- per-stage KV caches ``[n_stages, per_stage, B, H, max_seq, hd]`` sharded
  ``P("pp")`` — each device's cache slots never leave it;
- embeddings / ln_f / tied head replicated, applied outside the shard_map
  under plain GSPMD (same split as gpipe: keeps ``wte`` out of the manual
  program).

Schedule per token (or per prompt, for prefill): ``n_stages`` ticks; at
tick t only the device with ``axis_index == t`` runs its blocks
(``lax.cond`` — inactive devices skip the compute entirely), then the
activation hops one step along the ring. A single token therefore costs
``n_stages`` stage-computes + ``n_stages - 1`` hops of latency — the
inherent serial chain of inference pipelining — but zero host round trips,
which is what dominates the host-driven runner (VERDICT round 1, weak #7).

Ragged batches left-pad like the single-device engine (per-row position
offsets + ``k_valid_from`` masks, replicated across stages), so
``runtime.batcher`` multiplexes concurrent requests onto this decoder;
weight-only int8 stages and uneven partitions compose (see class doc).
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.gpt2 import GPT2Config, Params, apply_blocks, embed, final_logits
from ..ops.attention import KVCache
from ..runtime.engine import (GenerateResult, SamplingConfig, _split_keys,
                              _step_keys, prepare_generate, select_token)
from . import partition as Pt
from ._shard_compat import pcast_varying, shard_map


# Static-analysis contract (tools/graftcheck): the scope whose traced
# jaxpr the overlap lint walks — the manual pipeline step every compiled
# program (prefill and decode) runs its ticks through. The lint flags
# collectives sitting on a scan's loop-carry critical path fed by
# in-body compute (a serial transfer double-buffering would hide,
# TokenWeave-style); the two currently-serial handoffs here are
# baselined with justifications in tools/graftcheck/baseline.txt.
GRAFTCHECK_DECODE_ENTRY_POINTS = ("_pp_blocks",)

# Donation contract (tools/graftcheck sanitize pass): ``_decode``
# consumes the per-stage cache stacks (args 2 and 3) — callers re-bind
# both from the call's outputs; a host view of either taken before the
# call would read donated storage.
DONATED_ARGS = {"_decode": (2, 3)}

# Placement contract (tools/graftcheck placement pass + utils/
# graftshard): the decoder's long-lived holdings and its one traced
# program, by mesh position. The stage-major stacks (blocks, the
# validity mask) live split over ``pp``; the embed/head leaves every
# stage reads are EXPLICITLY replicated (tiny next to the blocks — the
# replicated-large-buffer rule holds the declaration to a byte
# threshold); ``_pp_blocks`` is the shard_map program whose traced
# jaxpr must establish exactly the ``pp`` placement it declares.
PLACEMENT_CONTRACT = {
    "mesh_axes": ("pp",),
    "holding:blocks": "pp",
    "holding:_valid": "pp",
    "holding:shared": "replicated",
    "entry:_pp_blocks": "pp",
}


def stage_ring_permutation(n_stages: int) -> list:
    """THE ppermute pairs for one hop along the stage ring:
    ``[(0, 1), (1, 2), ..., (n_stages - 2, n_stages - 1)]``.

    A *partial bijection* over the stage axis by construction — every
    source and every destination appears at most once, all in range.
    The last stage deliberately sends nowhere and stage 0 receives
    nothing (its lane is refilled by the scan carry); ``ppermute``
    zero-fills un-addressed destinations, which the tick schedule never
    reads. Declared as a named function (rather than inlined at the
    ``ppermute`` call) so the static verifier (tools/graftcheck) can
    check the bijection property per axis size without tracing the full
    pipelined program.
    """
    return [(j, j + 1) for j in range(n_stages - 1)]


class PipelinedDecoder:
    """N-stage pipelined generate as two compiled SPMD programs.

    Round-3 composition (VERDICT r2 weak #5: "the path that actually
    spans chips serves only plain rectangular fp32/bf16 single
    streams"): weight-only int8 stages (``dtype="int8"`` quantizes
    through ``ops.quant`` exactly like the single-device engine), ragged
    left-padded batches (per-row ``pad`` masks + position offsets, so
    ``runtime.batcher`` can multiplex requests onto this decoder), and
    uneven stage partitions (zero-padded stage-major stacking with
    identity masking, ``partition.stack_stage_params_padded``).
    """

    def __init__(self, params: Params, config: GPT2Config, mesh: Mesh,
                 max_seq: int, dtype=jnp.float32, pp_axis: str = "pp",
                 boundaries=None):
        if pp_axis not in mesh.axis_names:
            raise ValueError(f"mesh has no {pp_axis!r} axis: {mesh.axis_names}")
        if max_seq > config.n_positions:
            raise ValueError(
                f"max_seq={max_seq} exceeds n_positions={config.n_positions}")
        self.config = config
        self.mesh = mesh
        self.max_seq = max_seq
        # compiled cache width (no window buckets here): the attribute
        # the batcher's kv_block_gauges contract reads off any engine
        self._cache_seq = max_seq
        self.pp_axis = pp_axis
        self.n_stages = mesh.shape[pp_axis]

        # family dispatch through the registry's staging predicate: dense
        # GPT-2 and llama pipeline; MoE (whose expert tree has no stage
        # form) fails HERE with a clear error instead of deep in the scan
        from ..models import is_stage_partitionable
        from ..models.llama import LlamaConfig
        if not is_stage_partitionable(config):
            raise NotImplementedError(
                f"PipelinedDecoder covers the dense GPT-2 and llama "
                f"families; {type(config).__name__} decodes unstaged")
        self._llama = isinstance(config, LlamaConfig)
        # dtype validates against the DECLARED regime vocabulary
        # (graftnum.REGIMES) with a typed error, the same gate as
        # DecodeEngine — every engine-building path shares the one
        # mechanism, so an off-vocabulary dtype can't slip into a
        # sibling constructor's astype
        from ..utils.graftnum import engine_regime_of
        if engine_regime_of(dtype) == "int8":
            # same weight-only scheme as the single-device engine:
            # int8 kernels/embedding with per-channel scales, bf16
            # activations + KV cache (ops.quant)
            from ..ops.quant import quantize_params
            params = quantize_params(params, jnp.bfloat16)
            dtype = jnp.bfloat16
        else:
            cast = lambda x: (x.astype(dtype)
                              if jnp.issubdtype(x.dtype, jnp.floating) else x)
            params = jax.tree.map(cast, params)
        self.dtype = dtype
        bounds = (list(boundaries) if boundaries is not None
                  else Pt.balanced_boundaries(config.n_layer, self.n_stages))
        specs = Pt.make_stage_specs(config.n_layer, bounds)
        if len(specs) != self.n_stages:
            raise ValueError(
                f"boundaries {bounds} give {len(specs)} stages; the "
                f"mesh's pp axis has {self.n_stages} devices")
        if len({s.n_blocks for s in specs}) == 1:
            stacked = Pt.stack_stage_params(params, specs)
            self._valid = None
        else:
            # uneven partitions: stages zero-pad to the largest block
            # count and the pad layers mask to identity inside the scan
            stacked, self._valid = Pt.stack_stage_params_padded(params, specs)
        self.per_stage = max(s.n_blocks for s in specs)
        self.blocks = jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P(pp_axis))),
            stacked)
        if self._valid is not None:
            self._valid = jax.device_put(
                self._valid, NamedSharding(mesh, P(pp_axis)))
        rep = NamedSharding(mesh, P())
        self.shared = {
            k: jax.device_put(params[k], rep)
            for k in ("wte", "wpe", "ln_f", "lm_head") if k in params
        }

        self._prefill = jax.jit(self._prefill_impl)
        self._decode = jax.jit(self._decode_impl, donate_argnums=(2, 3),
                               static_argnames=("steps", "sampling"))

    # -- the manual pipeline step --------------------------------------------

    def _pp_blocks(self, blocks, ck_st, cv_st, h, length, pad=None):
        """[B,S,D] through all stages; returns (h, new ck_st, new cv_st).

        ``ck_st``/``cv_st``: ``[n_stages, per, B, H, max_seq, hd]``
        sharded over ``pp``; ``length`` replicated scalar (cache fill);
        ``pad`` ([B], replicated, optional) the ragged-batch left-pad
        prefixes — masked as attention keys on every stage."""
        pp, n_stages, config = self.pp_axis, self.n_stages, self.config
        has_valid = self._valid is not None
        has_pad = pad is not None

        def per_device(blocks_l, ck_l, cv_l, h, length, *extra):
            blocks_l = jax.tree.map(lambda x: x[0], blocks_l)  # [1,per,..]->[per,..]
            ck, cv = ck_l[0], cv_l[0]
            i = 0
            valid_l = pad_b = None
            if has_valid:
                valid_l = extra[i][0]          # [1, per] -> [per]
                i += 1
            if has_pad:
                pad_b = extra[i]               # [B]
            stage = jax.lax.axis_index(pp)
            h_var = pcast_varying(h, pp)
            final0 = pcast_varying(jnp.zeros_like(h), pp)

            def tick(carry, t):
                h_in, ck, cv, final = carry

                def run(args):
                    h_in, ck, cv = args
                    cache = KVCache(k=ck, v=cv, length=length)
                    if self._llama:
                        from ..models import llama
                        cos, sin = llama._angles(config, h_in.shape[1],
                                                 length, pad_b)
                        y, new_cache = llama.apply_blocks(
                            blocks_l, h_in, config, cos, sin, cache,
                            k_valid_from=pad_b, valid=valid_l)
                    else:
                        y, new_cache = apply_blocks(blocks_l, h_in, config,
                                                    cache,
                                                    k_valid_from=pad_b,
                                                    valid=valid_l)
                    return y, new_cache.k, new_cache.v

                y, ck, cv = jax.lax.cond(stage == t, run, lambda a: a,
                                         (h_in, ck, cv))
                # only the last tick's output on the last-stage device is
                # real; everything else is masked out after the scan
                final = jnp.where(t == n_stages - 1, y, final)
                incoming = jax.lax.ppermute(
                    y, pp, stage_ring_permutation(n_stages))
                return (incoming, ck, cv, final), None

            (_, ck, cv, final), _ = jax.lax.scan(
                tick, (h_var, ck, cv, final0), jnp.arange(n_stages))
            out = jnp.where(stage == n_stages - 1, final, 0)
            out = jax.lax.psum(out, pp)
            return out, ck[None], cv[None]

        in_specs = [P(pp), P(pp), P(pp), P(), P()]
        args = [blocks, ck_st, cv_st, h, length]
        if has_valid:
            in_specs.append(P(pp))
            args.append(self._valid)
        if has_pad:
            in_specs.append(P())
            args.append(pad)
        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=tuple(in_specs),
            out_specs=(P(), P(pp), P(pp)),
            axis_names={pp})(*args)

    # -- compiled programs ---------------------------------------------------

    def _fresh_cache(self, batch: int):
        heads = getattr(self.config, "n_kv_head", self.config.n_head)
        shape = (self.n_stages, self.per_stage, batch, heads,
                 self.max_seq, self.config.head_dim)
        sh = NamedSharding(self.mesh, P(self.pp_axis))
        return (jax.lax.with_sharding_constraint(jnp.zeros(shape, self.dtype), sh),
                jax.lax.with_sharding_constraint(jnp.zeros(shape, self.dtype), sh))

    def _embed(self, shared, ids, length, pad=None):
        if self._llama:
            from ..models import llama
            return llama._embed(shared, ids)   # RoPE: positions in attention
        offset = length if pad is None else length - pad[:, None]
        return embed(shared, ids, offset)

    def _head(self, shared, h):
        if self._llama:
            from ..models import llama
            return llama._final(shared, h, self.config)
        return final_logits({"ln_f": shared["ln_f"], "wte": shared["wte"]},
                            h, self.config.layer_norm_epsilon)

    def _prefill_impl(self, shared, blocks, ids, pad):
        ck, cv = self._fresh_cache(ids.shape[0])
        length = jnp.zeros((), jnp.int32)
        h = self._embed(shared, ids, length, pad)
        h, ck, cv = self._pp_blocks(blocks, ck, cv, h, length, pad)
        return self._head(shared, h)[:, -1], ck, cv

    def _decode_impl(self, shared, blocks, ck, cv, first_token, length0, key,
                     pad, *, steps: int, sampling: SamplingConfig):
        if steps == 1:
            return first_token[:, None], ck, cv

        def body(carry, step_key):
            token, ck, cv, length = carry
            h = self._embed(shared, token[:, None], length, pad)
            h, ck, cv = self._pp_blocks(blocks, ck, cv, h, length, pad)
            nxt = select_token(self._head(shared, h)[:, -1], sampling,
                               step_key)
            return (nxt, ck, cv, length + 1), nxt

        keys = _step_keys(key, steps - 1)
        (_, ck, cv, _), rest = jax.lax.scan(
            body, (first_token, ck, cv, length0), keys)
        tokens = jnp.concatenate([first_token[None, :], rest], axis=0)
        return tokens.T, ck, cv

    # -- public API ----------------------------------------------------------

    def generate(self, prompt_ids, max_new_tokens: int,
                 sampling: SamplingConfig = SamplingConfig(),
                 key: Optional[jax.Array] = None,
                 pad: Optional[np.ndarray] = None) -> GenerateResult:
        ids, batch, prompt_len, key, pad = prepare_generate(
            prompt_ids, max_new_tokens, self.max_seq, sampling, key, pad=pad)
        ids_j = jnp.asarray(ids, dtype=jnp.int32)
        # rectangular batches keep pad=None: the compiled programs skip
        # the per-row masks entirely (same convention as the engine)
        pad_j = jnp.asarray(pad) if pad.any() else None

        t0 = time.perf_counter()
        prefill_key, decode_key = _split_keys(key)
        last_logits, ck, cv = self._prefill(self.shared, self.blocks, ids_j,
                                            pad_j)
        first = select_token(last_logits, sampling, prefill_key)
        first.block_until_ready()
        t1 = time.perf_counter()
        length0 = jnp.asarray(prompt_len, jnp.int32)
        new, ck, cv = self._decode(self.shared, self.blocks, ck, cv, first,
                                   length0, decode_key, pad_j,
                                   steps=max_new_tokens, sampling=sampling)
        del ck, cv  # alias the donated prefill cache
        new = np.asarray(jax.block_until_ready(new))
        t2 = time.perf_counter()

        tokens = np.concatenate([ids, new], axis=1)
        return GenerateResult(tokens=tokens, prompt_len=prompt_len,
                              prefill_seconds=t1 - t0, decode_seconds=t2 - t1,
                              new_tokens=max_new_tokens,
                              decode_steps=max_new_tokens - 1,
                              pad=pad if pad.any() else None)
