"""1F1B pipeline-parallel training: one-forward-one-backward schedule.

``parallel.gpipe`` differentiates THROUGH the forward schedule: AD
transposes the forward scan into a full backward scan, so the program is
all-forwards-then-all-backwards — every stage must stash activations for
all M microbatches, and the schedule runs 2(M + S - 1) ticks. This
module hand-schedules the backward instead (the Megatron/PipeDream-style
upgrade the reference's layer-split serving topology never needed,
reference server.py:51-64 — its pipeline never trains):

- lockstep ticks ``t = 0 .. M + 2S - 3``; at tick t, stage s runs the
  FORWARD of microbatch ``t - s`` and the BACKWARD of microbatch
  ``t - (2S - 2 - s)`` (when in range). The last stage's backward of a
  microbatch starts in the SAME tick as its forward — the defining 1F1B
  interleaving — so cotangents chase activations down the pipe with
  ``S - 1`` ticks of lag instead of ``M + S - 1``.
- each stage stashes only its IN-FLIGHT microbatch inputs: at most
  ``min(M, 2S - 1)`` live entries (vs M for GPipe) — activation memory
  is bounded by pipeline depth, not schedule length, which is what lets
  M grow (and the bubble fraction (S-1)/(M+S-1) shrink) without memory
  blowing up.
- the backward recomputes the stage forward under ``jax.vjp``
  (activation rematerialization — the same trade GPipe's ``remat=True``
  path makes), so stash entries are single activations, not whole
  residual stacks.
- embedding and LM head/loss run INSIDE the program (stage 0 / last
  stage): the last stage needs per-microbatch loss cotangents the tick
  the microbatch arrives. Their grads accumulate locally and psum over
  ``pp`` at the end. GPT-2's tied head contributes to ``wte`` from both
  ends; the accumulation handles that naturally.
- like gpipe, only ``pp`` is a manual axis: dp/tp ride as automatic
  GSPMD axes (grad reductions over dp are inserted by the partitioner).

Returns (loss, grads) directly — there is no outer ``jax.grad``; the
train step applies the optimizer to the returned grads.  Losses match
``gpipe_lm_loss`` to reduction-order tolerance (same math, different
summation schedule); the dryrun ``check`` tolerance covers it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.gpt2 import GPT2Config, Params
from ._shard_compat import pcast_varying, shard_map
from .gpipe import microbatch

# Placement contract (tools/graftcheck placement pass + utils/
# graftshard): same manual-axis story as gpipe — ``pp`` is the only
# manual axis in the 1F1B program (dp grad reductions are GSPMD-
# inserted and never manual placement); the schedule's backward trace
# is too heavy for the compile-free traced half, so this contract is
# checked by the AST half (liveness + literal collective axes) only.
PLACEMENT_CONTRACT = {
    "mesh_axes": ("pp", "tp", "dp"),
    "entry:_compiled_1f1b": "pp",
}


def one_f_one_b_loss_and_grads(params: Params, ids: jnp.ndarray,
                               config: GPT2Config, mesh: Mesh,
                               n_microbatches: int,
                               valid: Optional[jnp.ndarray] = None,
                               pp_axis: str = "pp",
                               virtual_stages: int = 1):
    """LM loss + grads with blocks run under the 1F1B schedule.

    ``params`` uses the gpipe layout (``GPipeTrainStep.init``): family
    embed/head leaves replicated + ``stacked_blocks`` stage-major over
    ``pp`` (``[S, per, ...]`` for ``virtual_stages=1``, the interleaved
    ``[S, v, per_chunk, ...]`` layout otherwise). ``ids`` [B, S]; B must
    divide by ``n_microbatches``. Returns ``(loss, grads)`` with
    ``grads`` shaped exactly like ``params``.

    ``virtual_stages=v > 1`` selects INTERLEAVED 1F1B (Megatron-style):
    each device owns every S-th chunk of layers, so a microbatch makes v
    ring trips and the warm-up/drain bubble shrinks from ``(S-1)/M``
    fractions toward ``(S-1)/(vM)`` at the cost of v x ppermute volume
    and a v x wider stash.  CAVEAT: the bubble win needs the per-core
    ``lax.cond`` skip, which tp/sp meshes disable (collectives inside
    blocks); there the masked path computes every chunk every tick and
    interleaving only ADDS ticks (M + 2vS - 2 full-work ticks) — keep
    ``virtual_stages=1`` on tp/sp meshes.
    """
    if pp_axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {pp_axis!r} axis: {mesh.axis_names}")
    if virtual_stages > 1 and valid is not None:
        raise NotImplementedError(
            "interleaved 1F1B requires equal chunks (n_layer divisible "
            "by pp * virtual_stages); uneven boundaries are a "
            "virtual_stages=1 feature")
    ids_m = microbatch(jnp.asarray(ids, jnp.int32), n_microbatches)
    fn = _compiled_1f1b(mesh, config, pp_axis, n_microbatches,
                        valid is not None, virtual_stages)
    if valid is None:
        return fn(params, ids_m)
    valid = jax.device_put(valid, NamedSharding(mesh, P(pp_axis)))
    return fn(params, valid, ids_m)


@functools.lru_cache(maxsize=64)
def _compiled_1f1b(mesh: Mesh, config: GPT2Config, pp_axis: str,
                   n_micro: int, has_valid: bool, n_virtual: int = 1):
    """Build + jit the 1F1B program once per (mesh, config, schedule).

    Same caching rationale as ``gpipe._compiled_pipeline``: jit keys on
    function identity, and eager shard_map aborts on per-core control
    flow — the jit wrapper is required, and inlines for free inside the
    train step's outer jit.
    """
    n_stages = mesh.shape[pp_axis]
    vs_total = n_virtual * n_stages     # virtual pipeline depth
    n_ticks = n_micro + 2 * vs_total - 2
    # stash depth (per chunk): in-flight microbatches at virtual stage
    # vs are those with vs + m <= t < m + 2(VS-1) - vs + 1, at most
    # 2(VS-1-vs)+1 <= 2VS-1; one extra trash slot absorbs writes on
    # inactive ticks (cheaper than a predicated full-buffer select).
    k_stash = min(n_micro, 2 * vs_total - 1)

    from ..models.llama import LlamaConfig
    is_llama = isinstance(config, LlamaConfig)
    eps = getattr(config, "layer_norm_epsilon", None)

    def run_blocks(blocks_local, x, valid_row):
        if is_llama:
            from ..models import llama
            cos, sin = llama._angles(config, x.shape[1], 0, None)
            return llama.apply_blocks(blocks_local, x, config, cos, sin,
                                      valid=valid_row)[0]
        from ..models.gpt2 import apply_blocks
        return apply_blocks(blocks_local, x, config, valid=valid_row)[0]

    def embed_fwd(emb, ids_in):
        if is_llama:
            return emb["wte"][ids_in]
        s_in = ids_in.shape[-1]
        return emb["wte"][ids_in] + emb["wpe"][:s_in]

    def embed_bwd(emb, ids_in, dx):
        """Transpose of embed_fwd: gather -> scatter-add, (+ wpe row
        sums for GPT-2)."""
        g = {"wte": jnp.zeros_like(emb["wte"]).at[ids_in].add(
            dx.astype(emb["wte"].dtype))}
        if not is_llama:
            s_in = ids_in.shape[-1]
            g["wpe"] = jnp.zeros_like(emb["wpe"]).at[:s_in].add(
                dx.sum(axis=0).astype(emb["wpe"].dtype))
        return g

    def head_loss(head, y, tgt):
        """Per-microbatch MEAN next-token CE through ln_f + head."""
        if is_llama:
            from ..models import llama
            logits = llama._final(head, y, config)
        else:
            from ..models.gpt2 import final_logits
            logits = final_logits(head, y, eps)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), tgt)
        return jnp.mean(ce)

    # Collectives may not sit inside divergent per-core control flow;
    # blocks contain GSPMD-inserted all-reduces when tp/sp are real, so
    # the bubble/role conds only compile on pp(+dp) meshes — otherwise
    # every stage computes and the selects keep the math right.
    can_cond = all(mesh.shape.get(ax, 1) == 1 for ax in ("tp", "sp"))

    emb_keys = ("wte",) if is_llama else ("wte", "wpe")
    head_keys = ("ln_f", "lm_head") if is_llama else ("ln_f", "wte")

    def per_stage(blocks_local, valid_local, emb, head, ids_m):
        # local layout: [1, v, per_chunk, ...] -> per-chunk trees; chunk
        # j on device d is virtual stage j*S + d (interleaved; v=1 is
        # the flat schedule)
        blocks_local = jax.tree_util.tree_map(lambda x: x[0], blocks_local)
        chunks = [jax.tree_util.tree_map(lambda x, j=j: x[j], blocks_local)
                  for j in range(n_virtual)]
        valid_rows = (None if valid_local is None
                      else [valid_local[0][j] for j in range(n_virtual)])
        stage = jax.lax.axis_index(pp_axis)
        is_first = stage == 0
        is_last = stage == n_stages - 1

        mb, s_tot = ids_m.shape[1], ids_m.shape[2]
        s_in = s_tot - 1
        d = config.n_embd
        act = jnp.zeros((mb, s_in, d), jnp.float32)

        def vary(tree):
            # the scan carry becomes pp-varying via ppermute/role masks;
            # its signature must say so up front (same move as gpipe).
            # Leaves derived from pp-sharded INPUTS (zeros_like the local
            # block slice) are already varying — pcast rejects the no-op.
            def f(a):
                try:
                    return pcast_varying(a, pp_axis)
                except ValueError:
                    return a
            return jax.tree_util.tree_map(f, tree)

        # CRITICAL: differentiate wrt a pp-VARYING copy of the head
        # params. AD wrt a pp-invariant value inside the manual region
        # transposes the implicit invariant->varying broadcast into a
        # psum over pp — a hidden collective that (a) aborts inside
        # lax.cond branches and (b) sums every stage's (mostly garbage)
        # head grads in the masked path before the role mask applies.
        # With a varying head, grads stay per-stage; the single explicit
        # psum at the end does the cross-stage reduction once.
        head_v = vary(head)

        def head_grads_of(y, tgt):
            (loss_m, (dhead, dy)) = jax.value_and_grad(
                head_loss, argnums=(0, 1))(head_v, y, tgt)
            return loss_m, dhead, dy

        zero_gb = [jax.tree_util.tree_map(jnp.zeros_like, c)
                   for c in chunks]
        zero_gh = jax.tree_util.tree_map(jnp.zeros_like, head_v)
        zero_ge = jax.tree_util.tree_map(jnp.zeros_like, emb)

        init = vary(dict(
            fwd_in=[act] * n_virtual,
            bwd_in=[act] * n_virtual,
            stash=[jnp.zeros((k_stash + 1, mb, s_in, d), jnp.float32)
                   for _ in range(n_virtual)],
            gb=zero_gb,
            gh=zero_gh,
            ge=zero_ge,
            loss=jnp.float32(0.0),
        ))

        # v=1 keeps OPEN chains (no wrap edges): the wrapped payloads are
        # always discarded there (embed/dy_last overrides), so the two
        # wrap transfers per tick would be pure dead traffic. v>1 needs
        # the full ring — the wrap carries chunk j to chunk j+1.
        if n_virtual == 1:
            fwd_ring = [(i, i + 1) for i in range(n_stages - 1)]
            bwd_ring = [(i, i - 1) for i in range(1, n_stages)]
        else:
            fwd_ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            bwd_ring = [(i, (i - 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            stash = list(carry["stash"])
            gb = list(carry["gb"])
            gh, ge, loss_acc = carry["gh"], carry["ge"], carry["loss"]
            ys, dxs = [], []

            for j in range(n_virtual):
                bl_j, valid_j = chunks[j], (None if valid_rows is None
                                            else valid_rows[j])

                def fwd_of(x, bl_j=bl_j, valid_j=valid_j):
                    return run_blocks(bl_j, x, valid_j)

                def bwd_of(x, dy, bl_j=bl_j, valid_j=valid_j):
                    _, vjp = jax.vjp(
                        lambda bl, xx: run_blocks(bl, xx, valid_j),
                        bl_j, x)
                    return vjp(dy)

                vs = j * n_stages + stage          # virtual stage index
                m_f = t - vs                       # forward microbatch
                m_b = t - (2 * (vs_total - 1) - vs)  # backward microbatch
                act_f = (m_f >= 0) & (m_f < n_micro)
                act_b = (m_b >= 0) & (m_b < n_micro)
                mf_c = jnp.clip(m_f, 0, n_micro - 1)
                mb_c = jnp.clip(m_b, 0, n_micro - 1)
                ids_f = jax.lax.dynamic_index_in_dim(ids_m, mf_c, 0,
                                                     keepdims=False)
                ids_b = jax.lax.dynamic_index_in_dim(ids_m, mb_c, 0,
                                                     keepdims=False)

                # ---- forward slot ---------------------------------------
                x = carry["fwd_in"][j]
                if j == 0:  # only virtual stage 0 embeds fresh input
                    x = jnp.where(is_first,
                                  embed_fwd(emb, ids_f[:, :-1]), x)
                if can_cond:
                    y = jax.lax.cond(act_f, fwd_of, lambda x: x, x)
                else:
                    y = fwd_of(x)
                # stash this chunk's input; inactive ticks hit the trash
                # slot
                slot = jnp.where(act_f, mf_c % k_stash, k_stash)
                stash[j] = jax.lax.dynamic_update_index_in_dim(
                    stash[j], x, slot, axis=0)

                # final virtual stage: per-microbatch loss + cotangent,
                # SAME tick
                if j == n_virtual - 1:
                    last_work = is_last & act_f
                    if can_cond:
                        loss_m, dhead, dy_last = jax.lax.cond(
                            last_work,
                            lambda y, tgt: head_grads_of(y, tgt),
                            lambda y, tgt: (vary(jnp.float32(0.0)),
                                            zero_gh, jnp.zeros_like(y)),
                            y, ids_f[:, 1:])
                    else:
                        loss_m, dhead, dy_last = head_grads_of(
                            y, ids_f[:, 1:])
                        loss_m = jnp.where(last_work, loss_m, 0.0)
                        dhead = jax.tree_util.tree_map(
                            lambda g: jnp.where(last_work, g, 0.0), dhead)
                        dy_last = jnp.where(last_work, dy_last, 0.0)
                    loss_acc = loss_acc + loss_m
                    gh = jax.tree_util.tree_map(jnp.add, gh, dhead)

                # ---- backward slot --------------------------------------
                xb = jax.lax.dynamic_index_in_dim(
                    stash[j], mb_c % k_stash, 0, keepdims=False)
                dy = carry["bwd_in"][j]
                if j == n_virtual - 1:
                    dy = jnp.where(is_last, dy_last, dy)
                if can_cond:
                    dbl, dx = jax.lax.cond(
                        act_b, bwd_of,
                        lambda x, dy, j=j: vary((zero_gb[j],
                                                 jnp.zeros_like(x))),
                        xb, dy)
                else:
                    dbl, dx = bwd_of(xb, dy)
                    dbl = jax.tree_util.tree_map(
                        lambda g: jnp.where(act_b, g, 0.0), dbl)
                    dx = jnp.where(act_b, dx, 0.0)
                gb[j] = jax.tree_util.tree_map(jnp.add, gb[j], dbl)

                # virtual stage 0 pushes its input cotangent into the
                # embedding grads
                if j == 0:
                    first_work = is_first & act_b
                    if can_cond:
                        demb = jax.lax.cond(
                            first_work,
                            lambda ids_in, dx: vary(
                                embed_bwd(emb, ids_in, dx)),
                            lambda ids_in, dx: vary(zero_ge),
                            ids_b[:, :-1], dx)
                    else:
                        demb = embed_bwd(emb, ids_b[:, :-1], dx)
                        demb = jax.tree_util.tree_map(
                            lambda g: jnp.where(first_work, g, 0.0), demb)
                    ge = jax.tree_util.tree_map(jnp.add, ge, demb)

                ys.append(y)
                dxs.append(dx)

            # ---- ship activations down, cotangents up -------------------
            # Full rings (wrap included): chunk j's output feeds virtual
            # stage j*S+d+1 — device d+1's chunk j, except the wrap from
            # device S-1 to device 0's chunk j+1, handled by the roll
            # below. Device 0's chunk-0 slot receives the discarded
            # VS-1 wrap (embed overrides it at use time); mirrored for
            # cotangents, where the head cotangent overrides the last
            # device's chunk v-1 slot.
            recv_f = [jax.lax.ppermute(y, pp_axis, fwd_ring) for y in ys]
            recv_b = [jax.lax.ppermute(dx, pp_axis, bwd_ring)
                      for dx in dxs]
            fwd_in = [jnp.where(is_first, recv_f[(j - 1) % n_virtual],
                                recv_f[j]) for j in range(n_virtual)]
            bwd_in = [jnp.where(is_last, recv_b[(j + 1) % n_virtual],
                                recv_b[j]) for j in range(n_virtual)]

            carry = dict(fwd_in=fwd_in, bwd_in=bwd_in, stash=stash,
                         gb=gb, gh=gh, ge=ge, loss=loss_acc)
            return carry, None

        final, _ = jax.lax.scan(tick, init, jnp.arange(n_ticks))

        inv_m = 1.0 / n_micro
        loss = jax.lax.psum(final["loss"] * inv_m, pp_axis)
        # [v][per_chunk, ...] trees -> one [1, v, per_chunk, ...] tree
        # (leading axis restored for the P(pp) out_spec)
        gb = jax.tree_util.tree_map(
            lambda *gs: (jnp.stack(gs) * inv_m)[None], *final["gb"])
        gh = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g * inv_m, pp_axis), final["gh"])
        ge = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g * inv_m, pp_axis), final["ge"])
        return loss, gb, gh, ge

    def wrapped(params, valid, ids_m):
        emb = {k: params[k] for k in emb_keys}
        head = {k: params[k] for k in head_keys}
        blocks = params["stacked_blocks"]
        if n_virtual == 1:
            # legacy flat layout [S, per, ...] <-> internal [S, 1, per,
            # ...]; grads are squeezed back so the tree matches params
            blocks = jax.tree_util.tree_map(lambda x: x[:, None], blocks)
            if valid is not None:
                valid = valid[:, None]
        run = shard_map(
            per_stage if has_valid else
            (lambda b, e, h, i: per_stage(b, None, e, h, i)),
            mesh=mesh,
            in_specs=((P(pp_axis), P(pp_axis), P(), P(), P())
                      if has_valid else (P(pp_axis), P(), P(), P())),
            out_specs=(P(), P(pp_axis), P(), P()),
            axis_names={pp_axis})
        args = ((blocks, valid, emb, head, ids_m) if has_valid
                else (blocks, emb, head, ids_m))
        loss, gb, gh, ge = run(*args)
        if n_virtual == 1:
            gb = jax.tree_util.tree_map(lambda x: x[:, 0], gb)
        grads = {"stacked_blocks": gb}
        for k in emb_keys:
            grads[k] = ge[k]
        for k in head_keys:
            # GPT-2's tied head: wte grad = embed side + head side
            grads[k] = (grads[k] + gh[k]) if k in grads else gh[k]
        return loss, grads

    if has_valid:
        return jax.jit(wrapped)
    return jax.jit(lambda params, ids_m: wrapped(params, None, ids_m))
