"""Multi-device pipeline runtime: one stage per device, ICI handoff.

This is the runtime form of the reference's deployment topology — shard A
pod → coordinator relay → shard B pod over JSON/HTTP (reference
server.py:169-181) — rebuilt the TPU way: every stage's parameters and KV
cache live resident on their own device; the hidden-state hop between
stages is a direct device-to-device transfer (ICI on a real slice),
scheduled by XLA when stage i+1's jitted program consumes stage i's output.
The coordinator relay disappears entirely: nothing returns to the host
between stages except the final logits' sampled token.

Contrast of the per-token critical path:

  reference: tokenize → HTTP POST full sequence → torch fwd A → JSON
             encode [1,S,D] floats → HTTP relay → torch fwd B → JSON
             logits → numpy sampling           (2 HTTP round trips/token)
  here:      device0 embed+blocks → ICI xfer [B,1,D] → device1 blocks+head
             → on-device argmax → [B] int32 to host   (one tiny D2H/token)

The stage-per-device form keeps each stage's weights off every other chip
(the reference loads the full model in all three pods, server.py:108-110).
For the single-jit SPMD form used by training and microbatched inference,
see ``parallel.spmd`` (shard_map + ppermute over a pipeline mesh axis).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt2 import GPT2Config, Params
from ..ops.attention import KVCache
from ..runtime.engine import (GenerateResult, SamplingConfig,
                              prepare_generate, select_token)
from . import partition as P

# Donation contract (tools/graftcheck sanitize pass): every per-stage
# jit in ``_stage_fns`` consumes its cache argument (arg 2) — callers
# always continue with the RETURNED caches (see ``forward``'s docstring).
DONATED_ARGS = {"_stage_fns": (2,)}


class PipelineRunner:
    """N pipeline stages resident on N devices of a 1×N mesh.

    ``devices=None`` uses ``jax.devices()[:n_stages]``; with fewer physical
    devices than stages, stages wrap round-robin (useful on the single
    benchmark chip and matching the "roles on one box" degenerate case).
    """

    def __init__(self, params: Params, config: GPT2Config,
                 boundaries: Sequence[int], max_seq: int,
                 devices: Optional[Sequence[jax.Device]] = None,
                 dtype=jnp.float32):
        if max_seq > config.n_positions:
            raise ValueError(
                f"max_seq={max_seq} exceeds n_positions={config.n_positions}")
        self.config = config
        self.max_seq = max_seq
        self.dtype = dtype
        # declared-vocabulary gate first (typed reject of float16/fp8/
        # typos — the same graftnum.engine_regime_of mechanism
        # DecodeEngine uses; fp8 is a KV-block storage regime, not an
        # engine compute dtype), THEN the targeted int8 refusal (this
        # runner casts, and an astype to int8 would truncate floats,
        # not quantize)
        from ..utils.graftnum import engine_regime_of
        engine_regime_of(dtype)
        from ..ops.quant import reject_raw_int8
        reject_raw_int8(dtype)
        # inference compute dtype applies to the WEIGHTS too (the decode
        # bottleneck is streaming them), exactly as DecodeEngine casts —
        # dtype only sizing the KV cache would silently leave fp32
        # matmuls behind a bf16 label.
        params = jax.tree.map(
            lambda x: x.astype(dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        # make_stage_specs already enforces disjoint+exhaustive coverage;
        # validate_specs exists for externally supplied spec lists.
        self.specs = P.make_stage_specs(config.n_layer, boundaries)

        avail = list(devices) if devices is not None else jax.devices()
        self.devices = [avail[i % len(avail)] for i in range(len(self.specs))]

        # Each stage's param subset moves to its device once, at
        # construction — weights never transfer again (the reference
        # re-sends activations as JSON per token; weights it duplicates
        # everywhere).
        self.stage_params: List[Params] = [
            jax.device_put(sp, dev)
            for sp, dev in zip(P.partition_params(params, self.specs),
                               self.devices)
        ]
        # One jitted program per stage; placement follows the committed
        # stage params (and the explicitly transferred input, see
        # ``forward``). Donating the cache argument lets XLA update the KV
        # buffers in place.
        self._stage_fns = [
            jax.jit(lambda sp, x, cache, _spec=spec: P.stage_apply(
                sp, _spec, self.config, x, cache),
                    donate_argnums=(2,))
            for spec in self.specs
        ]

    @property
    def n_stages(self) -> int:
        return len(self.specs)

    def init_caches(self, batch: int) -> List[KVCache]:
        """Per-stage KV caches, each allocated on its stage's device."""
        return [
            jax.device_put(
                P.make_stage_cache(spec, self.config, batch, self.max_seq,
                                   self.dtype), dev)
            for spec, dev in zip(self.specs, self.devices)
        ]

    def forward(self, x: jnp.ndarray, caches: Optional[List[KVCache]] = None,
                ) -> Tuple[jnp.ndarray, Optional[List[KVCache]]]:
        """Run ids (or hidden states) through all stages in order.

        Returns final-stage output ([B,S,vocab] logits) and updated caches.
        The inter-stage transfer happens implicitly: stage i+1's jit
        consumes stage i's on-device output — on a multi-chip slice that is
        an ICI copy, never a host bounce.

        **Donation**: the supplied ``caches`` buffers are donated to XLA
        (updated in place on TPU) and must not be reused after this call —
        always continue with the *returned* caches, as ``generate`` does.
        """
        new_caches: Optional[List[KVCache]] = [] if caches is not None else None
        for i, fn in enumerate(self._stage_fns):
            cache_in = caches[i] if caches is not None else None
            # The inter-stage hop: move the activation to stage i's device
            # (ICI device-to-device on a slice; async, overlaps with the
            # previous stage's tail). This is the reference's HTTP relay
            # (server.py:172-181) reduced to one hardware copy.
            x = jax.device_put(x, self.devices[i])
            x, cache_out = fn(self.stage_params[i], x, cache_in)
            if new_caches is not None:
                new_caches.append(cache_out)
        return x, new_caches

    def generate(self, prompt_ids, max_new_tokens: int,
                 sampling: SamplingConfig = SamplingConfig(),
                 key: Optional[jax.Array] = None) -> GenerateResult:
        """Pipelined generate: prefill once, then cached per-token steps.

        The token loop is host-driven (each token must traverse all stages
        sequentially — inherent to inference pipelining), but every step
        moves only a [B,1,D] hidden slice between devices and a [B] token
        to the host. Validation (including the static cache-overflow
        guard) is shared with the single-device engine via
        ``runtime.engine.prepare_generate``.
        """
        ids, batch, prompt_len, key, _ = prepare_generate(
            prompt_ids, max_new_tokens, self.max_seq, sampling, key,
            allow_ragged=False)

        caches = self.init_caches(batch)
        ids_j = jnp.asarray(ids, dtype=jnp.int32)

        t0 = time.perf_counter()
        logits, caches = self.forward(ids_j, caches)
        step_key, key = jax.random.split(key)
        token = select_token(logits[:, -1], sampling, step_key)
        token.block_until_ready()
        t1 = time.perf_counter()

        out = [token]
        for _ in range(max_new_tokens - 1):
            logits, caches = self.forward(token[:, None], caches)
            step_key, key = jax.random.split(key)
            token = select_token(logits[:, -1], sampling, step_key)
            out.append(token)
        new = np.stack([np.asarray(t) for t in jax.block_until_ready(out)], axis=1)
        t2 = time.perf_counter()

        tokens = np.concatenate([ids, new], axis=1)
        return GenerateResult(tokens=tokens, prompt_len=prompt_len,
                              prefill_seconds=t1 - t0, decode_seconds=t2 - t1,
                              new_tokens=max_new_tokens,
                              decode_steps=max_new_tokens - 1)
