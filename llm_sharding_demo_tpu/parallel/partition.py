"""N-stage pipeline partitioner over the GPT-2 parameter pytree.

This is the reference's ``split_gpt2_model`` capability (reference
server.py:51-105: ShardA = wte+wpe+blocks[:k], ShardB = blocks[k:]+ln_f+
lm_head) generalized to N contiguous stages, with the validation the
reference lacks: its shipped k8s config runs block 1 on *both* shards
(SPLIT_AT=2 on shard A, SPLIT_AT=1 on shard B — SURVEY.md §2.3.1). Here the
partition is computed once from a single source of truth and checked to be
disjoint and exhaustive before any stage exists.

TPU-native design notes:

- Stage parameters are *slices of the stacked-block pytree* (blocks carry a
  leading layer axis, models.gpt2), so a stage's blocks still run as one
  ``lax.scan`` and extraction is pure array slicing — no module surgery.
- The LM head is tied to ``wte``, so the last stage carries ``wte`` too
  (shared with stage 0 only when n_stages == 1). This is the memory-honest
  version of the reference, where every role holds the *full* model
  (server.py:108-110).
- ``stage_apply`` is a pure function of (stage params, hidden|ids) suitable
  for jit per device or for shard_map over a pipeline mesh axis.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..models.gpt2 import (GPT2Config, Params, apply_blocks, embed,
                           final_logits)
from ..ops.attention import KVCache


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One pipeline stage: blocks ``[start, end)`` of ``n_layer`` total."""

    index: int
    n_stages: int
    start: int
    end: int

    @property
    def is_first(self) -> bool:
        return self.index == 0

    @property
    def is_last(self) -> bool:
        return self.index == self.n_stages - 1

    @property
    def n_blocks(self) -> int:
        return self.end - self.start


def balanced_boundaries(n_layer: int, n_stages: int) -> List[int]:
    """Split points giving each stage ``n_layer // n_stages`` (±1) blocks.

    Returns the interior boundaries, e.g. 12 layers / 4 stages -> [3, 6, 9].
    Earlier stages get the remainder blocks (they also carry the embedding).
    """
    if not 1 <= n_stages <= n_layer:
        raise ValueError(f"n_stages={n_stages} must be in [1, n_layer={n_layer}]")
    base, rem = divmod(n_layer, n_stages)
    sizes = [base + (1 if i < rem else 0) for i in range(n_stages)]
    bounds, acc = [], 0
    for s in sizes[:-1]:
        acc += s
        bounds.append(acc)
    return bounds


def make_stage_specs(n_layer: int, boundaries: Sequence[int],
                     ) -> List[StageSpec]:
    """Interior boundaries -> validated StageSpecs.

    Raises if the partition is not strictly increasing, in range, or leaves
    any stage empty — i.e. it enforces disjoint + exhaustive block coverage,
    the guard SURVEY.md §4 item 2 calls for against the reference's shipped
    SPLIT_AT mismatch.
    """
    bounds = list(boundaries)
    cuts = [0] + bounds + [n_layer]
    for a, b in zip(cuts, cuts[1:]):
        if not a < b:
            raise ValueError(
                f"invalid partition {bounds!r} of {n_layer} layers: stage "
                f"[{a},{b}) is empty or out of order (partition must be "
                "disjoint and exhaustive)")
    n_stages = len(cuts) - 1
    return [StageSpec(index=i, n_stages=n_stages, start=cuts[i], end=cuts[i + 1])
            for i in range(n_stages)]


def validate_specs(specs: Sequence[StageSpec], n_layer: int) -> None:
    """Re-check an externally supplied stage list, in composition order.

    Enforces everything ``stage_apply`` relies on: stages tile
    ``[0, n_layer)`` *in list order* (no sorting — order is execution
    order), and ``index``/``n_stages`` are consistent so exactly the first
    stage embeds and exactly the last applies the LM head.
    """
    pos = 0
    for i, s in enumerate(specs):
        if s.index != i or s.n_stages != len(specs):
            raise ValueError(
                f"spec at position {i} has index={s.index}, "
                f"n_stages={s.n_stages}; expected index={i}, "
                f"n_stages={len(specs)} (is_first/is_last would misfire)")
        if s.start != pos or s.end <= s.start:
            raise ValueError(
                f"stages {[(t.start, t.end) for t in specs]} do not tile "
                f"[0,{n_layer}) in order: gap/overlap at block {pos}")
        pos = s.end
    if pos != n_layer:
        raise ValueError(f"stages cover [0,{pos}) but model has {n_layer} layers")


def _slice_blocks(blocks: Params, start: int, end: int) -> Params:
    return jax.tree_util.tree_map(lambda x: x[start:end], blocks)


def extract_stage_params(params: Params, spec: StageSpec) -> Params:
    """The parameter subset one stage actually needs (and nothing more).

    First stage: embeddings + its blocks. Last stage: its blocks + the
    final norm and head. Middle stages: blocks only. Contrast with the
    reference, where every pod loads and keeps the full model
    (server.py:40-42, 108-110).

    Family is detected structurally: the llama tree carries an untied
    ``lm_head`` (and no ``wpe``); the GPT-2/MoE tree ties its head to
    ``wte``.
    """
    out: Params = {"blocks": _slice_blocks(params["blocks"], spec.start, spec.end)}
    llama_tree = "lm_head" in params
    if spec.is_first:
        out["wte"] = params["wte"]
        if not llama_tree:
            out["wpe"] = params["wpe"]
    if spec.is_last:
        out["ln_f"] = params["ln_f"]
        if llama_tree:
            out["lm_head"] = params["lm_head"]
        else:
            out["wte_out"] = params["wte"]  # tied LM head
    return out


def partition_params(params: Params, specs: Sequence[StageSpec]) -> List[Params]:
    """All stages' parameter subsets: ``[extract_stage_params(p, s) for s]``."""
    return [extract_stage_params(params, s) for s in specs]


def stage_apply(stage_params: Params, spec: StageSpec, config: GPT2Config,
                x: jnp.ndarray, cache: Optional[KVCache] = None,
                pad: Optional[jnp.ndarray] = None,
                decode_kernel=None,
                ) -> Tuple[jnp.ndarray, Optional[KVCache]]:
    """Run one stage. First stage takes ``[B,S]`` ids, others ``[B,S,D]``
    hidden states; last stage returns ``[B,S,vocab]`` logits.

    This is the per-stage public contract the reference exposes as
    ``/forward`` (ids -> hidden, server.py:132-140) and ``/forward_b``
    (hidden -> logits, server.py:143-151), as a pure jittable function.
    ``cache`` holds only this stage's layers (leading axis ``spec.n_blocks``).

    The position offset is *derived*, never passed: ``cache.length`` when a
    cache is present, else 0. A caller-supplied offset could desynchronize
    the wpe gather from the attention mask / cache-write position, which
    both always come from the cache — so the knob deliberately doesn't
    exist. ``pad`` ([B] int32) is the ragged-batch left-pad vector (see
    models.gpt2.forward_with_cache): it shifts positions down per row and
    masks each row's pad prefix as keys.
    """
    from ..models.llama import LlamaConfig
    if isinstance(config, LlamaConfig):
        return _stage_apply_llama(stage_params, spec, config, x, cache, pad,
                                  decode_kernel)
    position_offset = cache.length if cache is not None else 0
    if pad is not None:
        position_offset = position_offset - pad[:, None]
    h = embed(stage_params, x, position_offset) if spec.is_first else x
    h, cache = _stage_blocks_gpt2(stage_params, h, config, cache, pad,
                                  decode_kernel)
    if spec.is_last:
        head_params = {"ln_f": stage_params["ln_f"], "wte": stage_params["wte_out"]}
        h = final_logits(head_params, h, config.layer_norm_epsilon)
    return h, cache


def _stage_blocks_gpt2(stage_params, h, config, cache, pad, decode_kernel):
    """A stage's block stack: the whole-stack megakernel when the engine
    selected it (one launch for the stage's L_s layers instead of one
    per op — ``gpt2.mega_step``, THE shared family route), else the
    scanned per-layer path."""
    from ..models.gpt2 import mega_step
    from ..ops.decode_layer import mega_downgrade, mega_requested
    if mega_requested(decode_kernel, h.shape[1]) and cache is not None:
        step = mega_step(stage_params["blocks"], h, config, cache, pad,
                         decode_kernel)
        if step is not None:
            return step
        decode_kernel = mega_downgrade(decode_kernel)
    return apply_blocks(stage_params["blocks"], h, config, cache,
                        k_valid_from=pad, decode_kernel=decode_kernel)


def _stage_apply_llama(stage_params: Params, spec: StageSpec, config,
                       x: jnp.ndarray, cache: Optional[KVCache],
                       pad: Optional[jnp.ndarray], decode_kernel=None,
                       ) -> Tuple[jnp.ndarray, Optional[KVCache]]:
    """llama stage: RoPE angles derive from the stage cache's length (the
    same same-for-all-stages offset the dense path derives), embedding on
    the first stage, RMSNorm + untied head on the last."""
    from ..models import llama
    from ..ops.decode_layer import mega_downgrade, mega_requested
    offset = cache.length if cache is not None else 0
    cos, sin = llama._angles(config, x.shape[1], offset, pad)
    h = llama._embed(stage_params, x) if spec.is_first else x
    done = None
    if mega_requested(decode_kernel, h.shape[1]) and cache is not None:
        # the shared llama-family mega route (llama.mega_step): one
        # launch for this stage's blocks
        done = llama.mega_step(stage_params["blocks"], h, config, cache,
                               pad, cos, sin, decode_kernel)
        if done is None:
            decode_kernel = mega_downgrade(decode_kernel)
    if done is not None:
        h, cache = done
    else:
        h, cache = llama.apply_blocks(stage_params["blocks"], h, config,
                                      cos, sin, cache, k_valid_from=pad,
                                      decode_kernel=decode_kernel)
    if spec.is_last:
        h = llama._final(stage_params, h, config)
    return h, cache


def make_stage_cache(spec: StageSpec, config: GPT2Config, batch: int,
                     max_seq: int, dtype=jnp.float32) -> KVCache:
    """A KV cache sized for one stage's block count (kv-head width for
    GQA families — ``n_kv_head`` when the config defines it)."""
    if max_seq > config.n_positions:
        raise ValueError(
            f"max_seq={max_seq} exceeds n_positions={config.n_positions}")
    heads = getattr(config, "n_kv_head", config.n_head)
    return KVCache.create(spec.n_blocks, batch, heads, max_seq,
                          config.head_dim, dtype)


def stack_stage_params(params: Params, specs: Sequence[StageSpec]) -> Params:
    """Stage-major re-layout for single-jit pipelining over a mesh axis.

    Requires equal-size stages. Returns the block pytree reshaped from
    ``[n_layer, ...]`` to ``[n_stages, blocks_per_stage, ...]`` so a
    ``shard_map`` over the pipeline mesh axis gives each device its own
    ``[blocks_per_stage, ...]`` slice — the single-program SPMD form of the
    reference's multi-process topology.
    """
    sizes = {s.n_blocks for s in specs}
    if len(sizes) != 1:
        raise ValueError(
            f"stage-major stacking needs equal stage sizes, got "
            f"{[s.n_blocks for s in specs]}")
    per = sizes.pop()
    n_stages = len(specs)

    def reshape(x):
        return x.reshape((n_stages, per) + x.shape[1:])

    return jax.tree_util.tree_map(reshape, params["blocks"])


def stack_virtual_chunks(params: Params, n_stages: int,
                         n_virtual: int) -> Params:
    """Interleaved-1F1B re-layout: ``[L, ...]`` block leaves ->
    ``[n_stages, n_virtual, per_chunk, ...]`` with virtual chunk
    ``g = j * n_stages + d`` stored at ``[d, j]`` — device d owns every
    S-th chunk (the Megatron interleaved assignment), so one shard_map
    over the pp axis hands each device its ``[n_virtual, per_chunk,
    ...]`` slice. Requires ``L % (n_stages * n_virtual) == 0``.
    """
    def reshape(x):
        n_layer = x.shape[0]
        total = n_stages * n_virtual
        if n_layer % total:
            raise ValueError(
                f"interleaved stacking needs n_layer divisible by "
                f"pp * virtual_stages = {total}, got {n_layer}")
        per = n_layer // total
        # [L] in chunk-major order = [j, d, per]; devices want [d, j, per]
        return x.reshape((n_virtual, n_stages, per)
                         + x.shape[1:]).swapaxes(0, 1)

    return jax.tree_util.tree_map(reshape, params["blocks"])


def unstack_stage_params(stacked_blocks: Params) -> Params:
    """Inverse of ``stack_stage_params``: ``[S, per, ...]`` -> ``[L, ...]``."""
    def reshape(x):
        return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])

    return jax.tree_util.tree_map(reshape, stacked_blocks)


def stack_stage_params_padded(params: Params, specs: Sequence[StageSpec],
                              ) -> Tuple[Params, jnp.ndarray]:
    """Stage-major re-layout for ARBITRARY stage sizes.

    Stages are zero-padded to the largest stage's block count:
    ``[n_layer, ...]`` -> ``[n_stages, per_max, ...]`` plus a
    ``[n_stages, per_max]`` bool validity mask. Padding rows are all-zero
    parameters and are masked to identity inside the block scan
    (``models.gpt2.apply_blocks(valid=...)``), so the pipelined program
    matches the unpadded model exactly and padded params receive zero
    gradients (they stay zero under training; weight decay of zero is
    zero). This lifts the equal-stage restriction of
    ``stack_stage_params`` — e.g. 12 layers over 8 stages, or any uneven
    user-supplied BOUNDARIES.

    Cost: every stage *executes* ``per_max`` blocks, so a maximally uneven
    partition wastes ticks; balanced-but-uneven partitions (base+1 vs
    base) waste at most one block per stage.
    """
    per_max = max(s.n_blocks for s in specs)
    n_stages = len(specs)

    def pad_stack(x):
        rows = []
        for s in specs:
            piece = x[s.start:s.end]
            if s.n_blocks < per_max:
                pad_width = ((0, per_max - s.n_blocks),) + ((0, 0),) * (x.ndim - 1)
                piece = jnp.pad(piece, pad_width)
            rows.append(piece)
        return jnp.stack(rows)

    stacked = jax.tree_util.tree_map(pad_stack, params["blocks"])
    return stacked, stage_valid_mask(specs)


def stage_valid_mask(specs: Sequence[StageSpec]) -> jnp.ndarray:
    """[n_stages, per_max] bool: True where a stacked block row is a real
    layer, False where it is zero padding (see stack_stage_params_padded)."""
    per_max = max(s.n_blocks for s in specs)
    return jnp.asarray([[i < s.n_blocks for i in range(per_max)]
                        for s in specs])


def unstack_stage_params_padded(stacked_blocks: Params,
                                specs: Sequence[StageSpec]) -> Params:
    """Inverse of ``stack_stage_params_padded``: drop padding rows,
    concatenate the per-stage valid prefixes back to ``[n_layer, ...]``."""
    def merge(x):
        return jnp.concatenate([x[s.index, :s.n_blocks] for s in specs])

    return jax.tree_util.tree_map(merge, stacked_blocks)
