"""API-skew shim for ``shard_map``/``pcast`` across JAX versions.

The ``parallel/`` sibling of ``ops/_pallas_compat.py``: the manual SPMD
modules (``ppdecode``, ``gpipe``, ``pipeline_1f1b``,
``ops.ring_attention``) were written against the current JAX spelling —
``jax.shard_map(..., axis_names=...)`` plus ``jax.lax.pcast(x, axis,
to="varying")`` for varying-type carry signatures. Older JAX (0.4.x)
ships ``jax.experimental.shard_map.shard_map`` (axis names come from the
mesh, no varying types, ``check_rep`` instead) and no ``pcast`` at all,
so every manual pipeline program died at trace time with
``AttributeError`` on those containers.

Two shims, one semantic each:

- ``shard_map(f, mesh, in_specs, out_specs, axis_names)``: the new
  call shape, delegating to whichever implementation exists. The legacy
  path disables ``check_rep`` — replication checking is the old type
  system's stand-in for what varying types now track, and the manual
  ring programs here legitimately mix invariant and varying values
  (every replicated output is made so by an explicit ``psum``).
- ``pcast_varying(x, axis)``: mark a value axis-varying where varying
  types exist; identity where they don't (on legacy JAX every value is
  untyped with respect to the axis, so the no-op is exact).

Like the pallas shim, this keeps exactly one spelling at every call
site and quarantines the version probe here.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs, axis_names):
    """``jax.shard_map`` with the current signature, on any JAX."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, axis_names=axis_names)
    from jax.experimental.shard_map import shard_map as _legacy
    return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def pcast_varying(x, axis_name):
    """``jax.lax.pcast(x, axis, to="varying")`` where varying types
    exist; identity elsewhere (exact on legacy JAX — see module doc)."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axis_name, to="varying")
