"""Multi-host bootstrap: DCN glue so one program spans TPU hosts.

The reference's only "distributed backend" is synchronous HTTP/JSON
between single-host pods (reference server.py:172-181; SURVEY.md §2.2
last row). The TPU-native equivalent has two layers:

- **intra-slice (ICI)**: already covered everywhere else — device meshes,
  GSPMD annotations, ``ppermute``/``psum`` collectives (parallel.spmd,
  parallel.gpipe, parallel.ppdecode);
- **inter-host (DCN)**: this module. ``jax.distributed`` connects the
  per-host processes into one runtime: after ``initialize()``, every
  process sees the GLOBAL device set (``jax.devices()``), a single jitted
  program spans all hosts, and XLA routes collectives over ICI within a
  slice and DCN across slices. The same mesh/sharding code used on one
  host then works unchanged — which is the whole point: no NCCL/MPI-style
  separate codepath exists to port (SURVEY.md: the reference has none
  either).

Environment contract (standard JAX + k8s-friendly): ``COORDINATOR_ADDRESS``
(host:port of process 0), ``NUM_PROCESSES``, ``PROCESS_ID``. All three
unset means single-process (the common dev / single-pod case, a no-op);
set them together or get a startup error. Cloud TPU pod slices can
auto-detect these from TPU metadata via a bare
``jax.distributed.initialize()`` — deliberately NOT wired here, because
this module can't verify that path in this environment and a silent
half-initialized guess is worse than an explicit contract; call
``jax.distributed.initialize()`` yourself on managed pod slices.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger(__name__)

_initialized = False


def maybe_initialize(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Connect this process to the multi-host runtime if configured.

    Explicit arguments win over env vars. Returns True when
    ``jax.distributed.initialize`` ran (now or earlier), False for the
    single-process no-op. Must be called before the first backend use —
    same constraint jax.distributed itself imposes.
    """
    global _initialized
    if _initialized:
        return True
    addr = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    nproc = num_processes if num_processes is not None else (
        int(os.environ["NUM_PROCESSES"])
        if "NUM_PROCESSES" in os.environ else None)
    pid = process_id if process_id is not None else (
        int(os.environ["PROCESS_ID"])
        if "PROCESS_ID" in os.environ else None)

    if addr is None and nproc is None and pid is None:
        return False  # single-process: nothing to connect
    if addr is None or nproc is None or pid is None:
        raise ValueError(
            "partial multi-host config: COORDINATOR_ADDRESS, NUM_PROCESSES "
            "and PROCESS_ID must be set together "
            f"(got addr={addr!r}, nproc={nproc!r}, pid={pid!r})")
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=nproc, process_id=pid)
    _initialized = True
    log.info("joined multi-host runtime: process %d/%d via %s; "
             "%d global devices on %d processes", pid, nproc, addr,
             len(jax.devices()), jax.process_count())
    return True


def global_mesh(axes: Dict[str, int]) -> Mesh:
    """A mesh over the GLOBAL device set (all hosts), axes as given.

    Multi-host layout guidance baked in: the FIRST axis is the
    slowest-varying over the device list, and JAX orders global devices
    process-major — so put the data-parallel (or pipeline) axis first to
    make it the cross-host axis (gradient all-reduce / stage handoff over
    DCN once per step) and keep tensor/sequence axes inside a host's
    slice where collectives ride ICI per layer.
    """
    from .spmd import make_mesh

    try:
        return make_mesh(axes)
    except ValueError as e:
        raise ValueError(  # add the multi-process context to the count error
            f"{e} (global runtime spans {jax.process_count()} "
            "process(es))") from None


def shard_host_batch(local_batch, mesh: Mesh, axis: str = "dp"):
    """Per-host input pipeline -> one global sharded array.

    Each process passes its HOST-LOCAL batch shard (e.g. its slice of a
    dataset); the result is the global [sum-of-locals, ...] array sharded
    over ``axis``, built without any host ever materializing the full
    batch (``jax.make_array_from_process_local_data`` moves only local
    data to local devices; DCN is never touched for input).
    """
    sharding = NamedSharding(mesh, P(axis))
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_batch))
