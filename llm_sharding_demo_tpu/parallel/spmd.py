"""Mesh construction and GSPMD sharding rules (dp / tp / sp axes).

The reference has no notion of a device mesh — its only "distribution" is
three CPU pods talking JSON over HTTP (reference server.py:172-181;
SURVEY.md §2.2). This module is the TPU-native foundation the rest of the
framework shards over, following the standard XLA recipe: pick a mesh,
annotate shardings with ``NamedSharding``/``PartitionSpec``, let the XLA
SPMD partitioner insert the collectives (all-reduce/all-gather/
reduce-scatter ride ICI), profile, iterate.

Axes:

- ``dp``   — data parallel: batch dim of activations; gradients all-reduce
  over this axis (inserted by XLA from the sharding annotations).
- ``tp``   — tensor parallel, Megatron-style: attention QKV/out projections
  and MLP up/down projections column-/row-sharded so each chip holds
  ``1/tp`` of every block matmul; XLA inserts the two per-block
  all-reduces.
- ``sp``   — sequence parallel for activations: the sequence dim of hidden
  states outside attention; attention itself needs the full sequence, so
  XLA all-gathers at the block boundary (ring-attention kernels that avoid
  the gather live in ``ops.ring_attention``).
- ``pp``   — pipeline axis, used by the GPipe runtime (``parallel.gpipe``),
  not by the rules here.

Everything here is *annotation only* — no communication is hand-written.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.gpt2 import Params


def make_mesh(shape: Dict[str, int],
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named mesh, e.g. ``make_mesh({"dp": 2, "tp": 4})``.

    Validates the axis product against the device count instead of letting
    ``reshape`` fail cryptically.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = int(np.prod(list(shape.values())))
    if n != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices).reshape(tuple(shape.values()))
    return Mesh(arr, tuple(shape.keys()))


def param_pspecs(mesh: Mesh) -> Params:
    """PartitionSpec pytree matching ``models.gpt2`` params.

    Megatron layout over ``tp`` (axes not in the mesh are dropped, so the
    same rules serve a pure-dp mesh or a tp-only mesh):

    - ``c_attn``/``c_fc`` kernels: output dim sharded (column parallel);
    - ``c_proj`` kernels (attn and mlp): input dim sharded (row parallel);
    - matching biases sharded on their only dim for column-parallel layers,
      replicated for row-parallel (bias adds after the all-reduce);
    - embeddings, layer norms, ln_f: replicated (small, and keeping wte
      replicated keeps the tied head's logits matmul unconstrained).

    Note the c_attn kernel's fused ``3d`` output dim: sharding it over tp
    splits the q/k/v concatenation into ``tp`` contiguous chunks, which is
    numerically fine under GSPMD (it re-tiles at the ``jnp.split`` /
    head-reshape). The pipeline path (``parallel.gpipe``) reuses this same
    fused layout safely because tp remains an *automatic* axis inside its
    shard_map (only ``pp`` is manual) — a fully manual tp split would
    instead need a per-head re-layout so chunk boundaries don't cross
    q/k/v.
    """
    tp = "tp" if "tp" in mesh.axis_names else None

    def blk(spec_tail: Tuple[Any, ...]) -> P:
        # blocks carry a leading layer axis, never sharded
        return P(None, *spec_tail)

    return {
        "wte": P(),
        "wpe": P(),
        "blocks": {
            "ln_1": {"scale": blk((None,)), "bias": blk((None,))},
            "attn": {
                "c_attn": {"kernel": blk((None, tp)), "bias": blk((tp,))},
                "c_proj": {"kernel": blk((tp, None)), "bias": blk((None,))},
            },
            "ln_2": {"scale": blk((None,)), "bias": blk((None,))},
            "mlp": {
                "c_fc": {"kernel": blk((None, tp)), "bias": blk((tp,))},
                "c_proj": {"kernel": blk((tp, None)), "bias": blk((None,))},
            },
        },
        "ln_f": {"scale": P(), "bias": P()},
    }


def moe_param_pspecs(mesh: Mesh) -> Params:
    """PartitionSpecs for ``models.moe`` params: experts over ``ep``.

    Expert kernels are ``[L, E, d_in, d_out]``: the E axis shards over
    ``ep`` (each chip owns E/ep experts end to end; XLA turns the
    dispatch/combine einsums into all-to-alls), and the expert FFN hidden
    dim additionally shards over ``tp`` when present — Megatron layout
    *within* each expert. The router stays replicated: every token needs
    every expert's logit.
    """
    ep = "ep" if "ep" in mesh.axis_names else None
    tp = "tp" if "tp" in mesh.axis_names else None

    def blk(*tail) -> P:
        return P(None, *tail)

    return {
        "wte": P(),
        "wpe": P(),
        "blocks": {
            "ln_1": {"scale": blk(None), "bias": blk(None)},
            "attn": {
                "c_attn": {"kernel": blk(None, tp), "bias": blk(tp)},
                "c_proj": {"kernel": blk(tp, None), "bias": blk(None)},
            },
            "ln_2": {"scale": blk(None), "bias": blk(None)},
            "moe": {
                "router": {"kernel": blk(None, None)},
                "experts": {
                    "c_fc": {"kernel": blk(ep, None, tp), "bias": blk(ep, tp)},
                    "c_proj": {"kernel": blk(ep, tp, None), "bias": blk(ep, None)},
                },
            },
        },
        "ln_f": {"scale": P(), "bias": P()},
    }


def shard_moe_params(params: Params, mesh: Mesh) -> Params:
    return shard_params(params, mesh, moe_param_pspecs(mesh))


def llama_param_pspecs(mesh: Mesh) -> Params:
    """PartitionSpecs for ``models.llama`` params — Megatron layout.

    Same recipe as ``param_pspecs``: q/k/v and gate/up kernels
    column-parallel over ``tp`` (output dim sharded), wo/down row-parallel
    (input dim sharded), norms and embeddings replicated. No biases exist
    in this family. kv projections shard over tp only when
    ``n_kv_head`` divides tp cleanly — GSPMD handles uneven tiling but the
    annotation is still correct either way (it re-tiles at the head
    reshape, as with the fused GPT-2 qkv).
    """
    tp = "tp" if "tp" in mesh.axis_names else None

    def blk(spec_tail: Tuple[Any, ...]) -> P:
        return P(None, *spec_tail)

    return {
        "wte": P(),
        "blocks": {
            "ln_attn": {"scale": blk((None,))},
            "attn": {
                "wq": {"kernel": blk((None, tp))},
                "wk": {"kernel": blk((None, tp))},
                "wv": {"kernel": blk((None, tp))},
                "wo": {"kernel": blk((tp, None))},
            },
            "ln_mlp": {"scale": blk((None,))},
            "mlp": {
                "gate": {"kernel": blk((None, tp))},
                "up": {"kernel": blk((None, tp))},
                "down": {"kernel": blk((tp, None))},
            },
        },
        "ln_f": {"scale": P()},
        "lm_head": {"kernel": P()},
    }


def batch_pspec(mesh: Mesh) -> P:
    """[B, S] token batches: batch over dp, sequence over sp (if present)."""
    dp = "dp" if "dp" in mesh.axis_names else None
    sp = "sp" if "sp" in mesh.axis_names else None
    return P(dp, sp)


def shard_params(params: Params, mesh: Mesh, specs: Optional[Params] = None
                 ) -> Params:
    """device_put a param pytree with a PartitionSpec tree (default: the
    dense-GPT-2 ``param_pspecs`` layout)."""
    if specs is None:
        specs = param_pspecs(mesh)
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, specs)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
