"""GPipe-style pipeline-parallel block execution (shard_map + ppermute).

This is the *training-capable*, single-program form of pipeline
parallelism. Where ``parallel.pipeline.PipelineRunner`` mirrors the
reference's topology for serving (stage per device, host-driven handoff —
the TPU rebuild of reference server.py:169-181), this module runs all
stages inside ONE jitted SPMD program:

- transformer blocks are stacked stage-major ``[n_stages, per_stage, ...]``
  and sharded over the mesh's ``pp`` axis, so each device owns exactly its
  stage's weights;
- the classic GPipe schedule: the batch is split into M microbatches; at
  schedule tick t, stage i runs microbatch ``t - i``; activations hop to
  the next stage via ``lax.ppermute`` over the ICI ring. The pipeline
  "bubble" is the usual ``(S-1)/(M+S-1)`` fraction;
- reverse-mode AD differentiates straight through the schedule (the
  transpose of ``ppermute`` is the reverse ``ppermute``, of ``psum`` a
  broadcast), giving pipeline-parallel *training* for free — no hand-rolled
  backward schedule;
- the ``pp`` axis is the only *manual* axis: dp / tp / sp stay automatic
  (GSPMD), so the same step composes data, tensor, sequence, and pipeline
  parallelism on one mesh (see ``axis_names={pp_axis}`` on the shard_map).

The embedding and LM head run outside the shard_map under plain GSPMD:
with the tied head this keeps ``wte`` out of the manual program entirely
and lets XLA lay out the vocab matmul freely.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.gpt2 import GPT2Config, Params, apply_blocks
from ._shard_compat import pcast_varying, shard_map

# Placement contract (tools/graftcheck placement pass + utils/
# graftshard): ``pp`` is the single MANUAL axis here — the compiled
# pipeline program's traced jaxpr must establish exactly that placement
# (blocks split stage-major over pp, activations replicated). tp/sp
# ride as automatic GSPMD axes inside the blocks and never appear as
# manual placement in the traced program.
PLACEMENT_CONTRACT = {
    "mesh_axes": ("pp", "tp", "sp"),
    "entry:_compiled_pipeline": "pp",
}


def microbatch(h: jnp.ndarray, n_microbatches: int) -> jnp.ndarray:
    """[B, ...] -> [M, B/M, ...]; validates divisibility."""
    b = h.shape[0]
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches={n_microbatches}")
    return h.reshape((n_microbatches, b // n_microbatches) + h.shape[1:])


def unmicrobatch(h: jnp.ndarray) -> jnp.ndarray:
    """[M, mb, ...] -> [M*mb, ...]."""
    return h.reshape((h.shape[0] * h.shape[1],) + h.shape[2:])


def gpipe_apply_blocks(stacked_blocks: Params, h_micro: jnp.ndarray,
                       config: GPT2Config, mesh: Mesh,
                       pp_axis: str = "pp", remat: bool = False,
                       valid: Optional[jnp.ndarray] = None,
                       ) -> jnp.ndarray:
    """Run stage-major stacked blocks over microbatched hidden states.

    ``stacked_blocks`` leaves: ``[n_stages, per_stage, ...]`` sharded
    ``P(pp_axis, ...)``; ``h_micro``: ``[M, mb, seq, D]`` replicated over
    ``pp`` (dp/sp sharding on mb/seq rides along as automatic axes).
    Returns ``[M, mb, seq, D]``.

    ``valid`` ([n_stages, per_stage] bool) marks real vs padding block
    rows for unequal stage sizes (``partition.stack_stage_params_padded``);
    padding rows run but are masked to identity. ``None`` means all rows
    are real (the equal-stage layout).

    Schedule: T = M + S - 1 ticks via ``lax.scan``. Stage 0 feeds
    microbatch t (clamped; overrun ticks recompute a stale microbatch whose
    output lands in an already-finalized slot — masked writes keep later
    real values authoritative). The last stage's finished microbatch
    ``t - (S-1)`` accumulates into the output buffer; a masked ``psum``
    replicates the final buffer across the pp axis so the caller's head/
    loss math is pp-invariant.
    """
    if pp_axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {pp_axis!r} axis: {mesh.axis_names}")
    n_micro = h_micro.shape[0]
    fn = _compiled_pipeline(mesh, config, pp_axis, remat, n_micro,
                            valid is not None)
    if valid is None:
        return fn(stacked_blocks, h_micro)
    valid = jax.device_put(valid, NamedSharding(mesh, P(pp_axis)))
    return fn(stacked_blocks, valid, h_micro)


@functools.lru_cache(maxsize=64)
def _compiled_pipeline(mesh: Mesh, config: GPT2Config, pp_axis: str,
                       remat: bool, n_micro: int, has_valid: bool):
    """Build + jit the pipeline program once per (mesh, config, schedule).

    Cached on hashable keys because jit's own cache is keyed on function
    identity — rebuilding the shard_map closure per call would make every
    eager call re-trace AND re-XLA-compile the whole S-stage scan. The
    jit wrapper itself is required: EAGER shard_map hard-aborts (not
    raises) on the per-core lax.cond below in current JAX; under jit the
    same program compiles and runs correctly. Inside an outer jit (the
    train step) the inner jit is inlined for free.
    """
    n_stages = mesh.shape[pp_axis]
    n_ticks = n_micro + n_stages - 1
    # Family dispatch (static: config is in this function's cache key).
    # llama blocks need RoPE angles; positions are 0..S-1 for the whole
    # (no-cache) training forward, identical on every stage and tick.
    from ..models.llama import LlamaConfig
    is_llama = isinstance(config, LlamaConfig)

    def run_blocks(blocks_local, x, valid_row):
        if is_llama:
            from ..models import llama
            # same helper forward() uses: positions 0..S-1, no pad
            cos, sin = llama._angles(config, x.shape[1], 0, None)
            return llama.apply_blocks(blocks_local, x, config, cos, sin,
                                      remat=remat, valid=valid_row)[0]
        return apply_blocks(blocks_local, x, config, remat=remat,
                            valid=valid_row)[0]
    # Bubble ticks can skip the block FLOPs via a per-core lax.cond — but
    # only when the block computation contains no cross-device collectives:
    # tp/sp shard the matmuls/sequence and XLA's partitioner inserts
    # all-reduces inside the block, and collectives inside divergent
    # control flow abort. pp-only (±dp, which all-reduces grads outside
    # the blocks) is the common fast case; tp/sp meshes keep the
    # compute-and-mask schedule.
    skip_bubbles = all(mesh.shape.get(ax, 1) == 1 for ax in ("tp", "sp"))

    def per_stage(blocks_local: Params, valid_local,
                  h_all: jnp.ndarray) -> jnp.ndarray:
        # local view: [1, per_stage, ...] -> [per_stage, ...]
        blocks_local = jax.tree_util.tree_map(lambda x: x[0], blocks_local)
        valid_row = None if valid_local is None else valid_local[0]
        stage = jax.lax.axis_index(pp_axis)
        zeros_state = jnp.zeros(h_all.shape[1:], h_all.dtype)
        # mark the scan carry as pp-varying up front (it becomes varying
        # via ppermute/masked writes; the carry signature must agree)
        init = (pcast_varying(zeros_state, pp_axis),
                pcast_varying(jnp.zeros_like(h_all), pp_axis))

        def tick(carry, t):
            state, outputs = carry
            feed = jax.lax.dynamic_index_in_dim(
                h_all, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
            x = jnp.where(stage == 0, feed, state)
            if skip_bubbles:
                # bubble ticks (stage i is idle before tick i and after
                # tick i + M - 1) skip the block FLOPs entirely: inside
                # shard_map this cond is real per-core control flow — each
                # TPU core has its own program counter, and the collective
                # (ppermute below) stays OUTSIDE the cond so every core
                # still joins it. With M microbatches on S stages this
                # recovers the (S-1)/(M+S-1) bubble fraction round 1
                # burned on recomputing stale microbatches.
                active = (t >= stage) & (t < stage + n_micro)
                y = jax.lax.cond(
                    active,
                    lambda x: run_blocks(blocks_local, x, valid_row),
                    lambda x: x,
                    x)
            else:
                y = run_blocks(blocks_local, x, valid_row)
            # hop to the next stage over the ICI ring; stage 0 receives
            # zeros (it is fed from h_all, never from a predecessor)
            incoming = jax.lax.ppermute(
                y, pp_axis, [(j, j + 1) for j in range(n_stages - 1)])
            done = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            written = jax.lax.dynamic_update_index_in_dim(
                outputs, y, done, axis=0)
            outputs = jnp.where(stage == n_stages - 1, written, outputs)
            return (incoming, outputs), None

        (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(n_ticks))
        # only the last stage holds real outputs; masked psum replicates
        outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
        return jax.lax.psum(outputs, pp_axis)

    if not has_valid:
        return jax.jit(shard_map(
            lambda b, h: per_stage(b, None, h), mesh=mesh,
            in_specs=(P(pp_axis), P()), out_specs=P(),
            axis_names={pp_axis}))
    return jax.jit(shard_map(
        per_stage, mesh=mesh,
        in_specs=(P(pp_axis), P(pp_axis), P()), out_specs=P(),
        axis_names={pp_axis}))


def stacked_block_pspecs(mesh: Mesh, pp_axis: str = "pp",
                         llama: bool = False, n_lead: int = 1) -> Params:
    """PartitionSpecs for stage-major stacked blocks: stage axis on ``pp``,
    plus the Megatron tp layout (shifted one axis right of
    ``spmd.param_pspecs`` / ``spmd.llama_param_pspecs`` because of the
    extra leading stage axis). ``n_lead=2`` covers the interleaved
    ``[S, v, per_chunk, ...]`` layout (an extra unsharded chunk axis)."""
    tp = "tp" if "tp" in mesh.axis_names else None

    def s(*tail):
        return P(pp_axis, *([None] * n_lead), *tail)

    if llama:
        return {
            "ln_attn": {"scale": s(None)},
            "attn": {
                "wq": {"kernel": s(None, tp)},
                "wk": {"kernel": s(None, tp)},
                "wv": {"kernel": s(None, tp)},
                "wo": {"kernel": s(tp, None)},
            },
            "ln_mlp": {"scale": s(None)},
            "mlp": {
                "gate": {"kernel": s(None, tp)},
                "up": {"kernel": s(None, tp)},
                "down": {"kernel": s(tp, None)},
            },
        }
    return {
        "ln_1": {"scale": s(None), "bias": s(None)},
        "attn": {
            "c_attn": {"kernel": s(None, tp), "bias": s(tp)},
            "c_proj": {"kernel": s(tp, None), "bias": s(None)},
        },
        "ln_2": {"scale": s(None), "bias": s(None)},
        "mlp": {
            "c_fc": {"kernel": s(None, tp), "bias": s(tp)},
            "c_proj": {"kernel": s(tp, None), "bias": s(None)},
        },
    }


def shard_stacked_blocks(stacked: Params, mesh: Mesh, pp_axis: str = "pp",
                         config=None, n_lead: int = 1) -> Params:
    """Place stage-major stacked blocks on the mesh; the family's pspec
    table is chosen from ``config`` (GPT-2 layout when None, for
    pre-llama callers)."""
    from ..models.llama import LlamaConfig
    specs = stacked_block_pspecs(mesh, pp_axis,
                                 llama=isinstance(config, LlamaConfig),
                                 n_lead=n_lead)
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        stacked, specs)
