"""Training subsystem: next-token LM loss + mesh-sharded optax train step.

The reference is inference-only (SURVEY.md §2.2: no gradient logic exists
anywhere in its ~500 LoC), so this subsystem has no counterpart to mirror —
it is designed TPU-first from scratch:

- the train step is ONE jitted program: forward (optionally rematerialized,
  ``jax.checkpoint`` per block), backward, optimizer update;
- distribution is pure GSPMD: parameters carry the Megatron tp layout and
  batches the dp/sp layout from ``parallel.spmd``; XLA derives every
  collective (gradient all-reduce over dp, activation collectives over
  tp/sp) from the annotations — no hand-written communication;
- optimizer state inherits each parameter's sharding, so Adam moments are
  sharded exactly like their weights (no replicated-optimizer memory bloat).

The manual pipeline-parallel training step (pp axis, explicit microbatch
schedule + ppermute) lives in ``parallel.gpipe``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import gpt2
from ..models.gpt2 import GPT2Config, Params
from ..parallel import spmd


def lm_loss(params: Params, ids: jnp.ndarray, config: GPT2Config,
            remat: bool = False, mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """Mean next-token cross-entropy over ``ids`` [B, S] (S >= 2).

    Logits for positions ``0..S-2`` predict tokens ``1..S-1``. The softmax
    cross-entropy runs in float32 regardless of activation dtype. ``mesh``
    reaches the forward for ``attention_impl="ring"`` (sequence-parallel
    attention over the sp axis).
    """
    # Family dispatch: gpt2 and llama share the forward signature; MoE has
    # its own loss (router aux term) via MoETrainStep's loss_fn override.
    from ..models import family_module
    logits = family_module(config).forward(params, ids[:, :-1], config,
                                           remat=remat, mesh=mesh)
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), ids[:, 1:])
    return jnp.mean(losses)


@dataclasses.dataclass
class TrainStep:
    """A compiled train step bound to (config, optimizer, mesh).

    ``init(params)`` shards params + builds matching-sharded optimizer
    state; ``__call__(params, opt_state, ids)`` returns updated
    ``(params, opt_state, loss)`` — one XLA program end to end.

    ``loss_fn`` (``(params, ids) -> scalar``) and ``pspec_fn``
    (``mesh -> PartitionSpec tree``) default to the dense GPT-2 LM loss
    and Megatron layout; ``MoETrainStep`` rebinds them for the MoE family.
    """

    config: Any
    optimizer: optax.GradientTransformation
    mesh: Optional[Mesh] = None
    remat: bool = False
    loss_fn: Optional[Callable] = None
    pspec_fn: Callable = spmd.param_pspecs

    def __post_init__(self):
        loss_fn = self.loss_fn or (
            lambda p, ids: lm_loss(p, ids, self.config, self.remat,
                                   mesh=self.mesh))

        def step(params, opt_state, ids):
            loss, grads = jax.value_and_grad(loss_fn)(params, ids)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        if self.mesh is None:
            self._step = jax.jit(step)
        else:
            # Sharding in = sharding out for params/opt state: the update is
            # elementwise, so XLA keeps everything resident; only the loss
            # (and dp/tp grad all-reduces internally) crosses chips.
            self._step = jax.jit(
                step, in_shardings=None,
                out_shardings=(None, None, spmd.replicated(self.mesh)))

    def init(self, params: Params) -> Tuple[Params, Any]:
        """Shard params per the mesh rules; init optimizer state likewise.

        ``optimizer.init`` runs eagerly on purpose: eager ``zeros_like`` on
        a sharded param yields identically sharded optimizer moments,
        whereas under an unannotated ``jit`` the output sharding is not
        guaranteed to follow.
        """
        if self.mesh is not None:
            params = spmd.shard_params(params, self.mesh,
                                       self.pspec_fn(self.mesh))
        opt_state = self.optimizer.init(params)
        return params, opt_state

    def shard_batch(self, ids) -> jnp.ndarray:
        ids = jnp.asarray(ids, dtype=jnp.int32)
        if self.mesh is None:
            return ids
        return jax.device_put(
            ids, NamedSharding(self.mesh, spmd.batch_pspec(self.mesh)))

    def __call__(self, params, opt_state, ids):
        return self._step(params, opt_state, ids)


def moe_lm_loss(params: Params, ids: jnp.ndarray, config,
                aux_weight: float = 0.01) -> jnp.ndarray:
    """Next-token CE + router load-balance auxiliary loss (models.moe)."""
    from ..models import moe

    logits, aux = moe.forward(params, ids[:, :-1], config)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), ids[:, 1:])
    return jnp.mean(ce) + aux_weight * aux


def LlamaTrainStep(config, optimizer: optax.GradientTransformation,
                   mesh: Optional[Mesh] = None,
                   remat: bool = False) -> TrainStep:
    """llama-family train step: the shared LM loss (lm_loss dispatches on
    the config family) with the llama Megatron pspec table bound."""
    return TrainStep(config, optimizer, mesh=mesh, remat=remat,
                     pspec_fn=spmd.llama_param_pspecs)


def MoETrainStep(config, optimizer: optax.GradientTransformation,
                 mesh: Optional[Mesh] = None,
                 aux_weight: float = 0.01) -> TrainStep:
    """Expert-parallel train step: experts sharded over ``ep`` (plus dp/tp
    as available), all collectives derived by GSPMD from the annotations
    in ``spmd.moe_param_pspecs``. A ``TrainStep`` with the MoE loss and
    pspec table bound."""
    return TrainStep(
        config, optimizer, mesh=mesh,
        loss_fn=lambda p, ids: moe_lm_loss(p, ids, config, aux_weight),
        pspec_fn=spmd.moe_param_pspecs)


def gpipe_lm_loss(params: Params, ids: jnp.ndarray, config: GPT2Config,
                  mesh: Mesh, n_microbatches: int,
                  remat: bool = False,
                  valid: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """LM loss with the blocks run pipeline-parallel (``parallel.gpipe``).

    ``params`` uses the gpipe layout: the family's embed/head leaves
    (GPT-2: ``wte``/``wpe``/``ln_f`` with the tied head; llama: ``wte``/
    ``ln_f``/untied ``lm_head``) plus ``stacked_blocks`` (stage-major,
    sharded over ``pp``) — exactly what ``GPipeTrainStep.init`` builds.
    Embed and head run under plain GSPMD outside the manual pipeline
    program. ``valid`` is the padding mask for unequal stage sizes (see
    ``parallel.partition.stack_stage_params_padded``).
    """
    from ..models.llama import LlamaConfig
    from ..parallel import gpipe  # local import: avoids a cycle at package init

    is_llama = isinstance(config, LlamaConfig)
    if is_llama:
        from ..models import llama
        h = llama._embed(params, ids[:, :-1])
    else:
        h = gpt2.embed(params, ids[:, :-1], 0)
    hm = gpipe.microbatch(h, n_microbatches)
    hm = gpipe.gpipe_apply_blocks(params["stacked_blocks"], hm, config, mesh,
                                  remat=remat, valid=valid)
    h = gpipe.unmicrobatch(hm)
    if is_llama:
        logits = llama._final(params, h, config)
    else:
        logits = gpt2.final_logits(params, h, config.layer_norm_epsilon)
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), ids[:, 1:])
    return jnp.mean(losses)


@dataclasses.dataclass
class GPipeTrainStep:
    """Pipeline-parallel train step: pp manual (GPipe schedule), dp/tp/sp
    automatic — the full composition on one mesh, one jitted program.

    ``init(params)`` converts a standard param pytree into the gpipe layout
    (stage-major stacked blocks) and shards it; the optimizer state follows
    each leaf's sharding (eager init, see ``TrainStep.init``). Stage sizes
    need NOT be equal: uneven partitions (n_layer not divisible by pp, or
    explicit uneven ``boundaries``) use zero-padded stacking with identity
    masking (``partition.stack_stage_params_padded``), at the cost of every
    stage executing the largest stage's block count.

    ``boundaries``: optional interior split points (the serving BOUNDARIES
    contract, ``utils.config``); must produce exactly ``pp`` stages.
    Default: ``partition.balanced_boundaries``.
    """

    config: GPT2Config
    optimizer: optax.GradientTransformation
    mesh: Mesh
    n_microbatches: int = 4
    remat: bool = False
    boundaries: Optional[Any] = None
    # "gpipe": AD through the forward schedule (all-fwd-then-all-bwd;
    # stashes M microbatches). "1f1b": hand-scheduled one-forward-one-
    # backward (parallel.pipeline_1f1b; stash bounded by min(M, 2S-1) —
    # raise n_microbatches to shrink the bubble without memory blowup).
    schedule: str = "gpipe"
    # >1 selects INTERLEAVED 1F1B: each device owns every pp-th chunk of
    # layers (Megatron virtual stages), shrinking the bubble by the
    # interleave factor at the cost of v x ring traffic. Requires
    # schedule="1f1b", default boundaries, n_layer % (pp * v) == 0.
    # On tp/sp meshes the bubble skip is disabled (collectives inside
    # blocks) and interleaving only adds ticks — keep v=1 there.
    virtual_stages: int = 1

    def __post_init__(self):
        from ..models import is_stage_partitionable
        from ..parallel import partition as P_

        if not is_stage_partitionable(self.config):
            raise NotImplementedError(
                f"GPipe covers the dense GPT-2 and llama families; "
                f"{type(self.config).__name__} trains via its GSPMD step "
                "(MoETrainStep)")
        if "pp" not in self.mesh.axis_names:
            raise ValueError(f"mesh {self.mesh.axis_names} has no 'pp' axis")
        if self.schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule={self.schedule!r} not one of ('gpipe', '1f1b')")
        pp = self.mesh.shape["pp"]
        if self.virtual_stages < 1:
            raise ValueError(
                f"virtual_stages={self.virtual_stages} must be >= 1")
        if self.virtual_stages > 1:
            if self.schedule != "1f1b":
                raise ValueError(
                    "virtual_stages > 1 (interleaved scheduling) "
                    "requires schedule='1f1b'")
            if self.boundaries is not None:
                raise ValueError(
                    "interleaved 1F1B uses equal chunks; explicit "
                    "boundaries are a virtual_stages=1 feature")
            if self.config.n_layer % (pp * self.virtual_stages):
                raise ValueError(
                    f"n_layer={self.config.n_layer} must divide by "
                    f"pp * virtual_stages = {pp * self.virtual_stages}")
            bad_axes = [ax for ax in ("tp", "sp")
                        if self.mesh.shape.get(ax, 1) > 1]
            if bad_axes:
                raise ValueError(
                    f"interleaved 1F1B on a {'/'.join(bad_axes)} mesh is "
                    "strictly slower: collectives inside blocks disable "
                    "the per-core bubble skip, so every tick computes "
                    "every chunk and interleaving only adds ticks — use "
                    "virtual_stages=1 (see parallel.pipeline_1f1b)")
        bounds = (list(self.boundaries) if self.boundaries is not None
                  else P_.balanced_boundaries(self.config.n_layer, pp))
        self._specs = P_.make_stage_specs(self.config.n_layer, bounds)
        if len(self._specs) != pp:
            raise ValueError(
                f"boundaries {bounds} give {len(self._specs)} stages; the "
                f"mesh's pp axis has {pp} devices")
        self._equal = len({s.n_blocks for s in self._specs}) == 1
        # valid mask only materializes for unequal partitions; the equal
        # case keeps the mask-free (slightly cheaper) program.
        self._valid = None if self._equal else P_.stage_valid_mask(self._specs)

        if self.schedule == "1f1b":
            from ..parallel.pipeline_1f1b import one_f_one_b_loss_and_grads

            def loss_and_grads(params, ids):
                return one_f_one_b_loss_and_grads(
                    params, ids, self.config, self.mesh,
                    self.n_microbatches, self._valid,
                    virtual_stages=self.virtual_stages)
        else:
            def loss_and_grads(params, ids):
                return jax.value_and_grad(gpipe_lm_loss)(
                    params, ids, self.config, self.mesh,
                    self.n_microbatches, self.remat, self._valid)

        def step(params, opt_state, ids):
            loss, grads = loss_and_grads(params, ids)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._step = jax.jit(
            step, out_shardings=(None, None, spmd.replicated(self.mesh)))

    def init(self, params: Params):
        from ..parallel import gpipe, partition as P_

        if self.virtual_stages > 1:
            # interleaved layout [S, v, per_chunk, ...]: device d owns
            # every S-th chunk (chunk j*S + d at [d, j])
            stacked = P_.stack_virtual_chunks(
                params, self.mesh.shape["pp"], self.virtual_stages)
            n_lead = 2
        elif self._equal:
            stacked = P_.stack_stage_params(params, self._specs)
            n_lead = 1
        else:
            stacked, _ = P_.stack_stage_params_padded(params, self._specs)
            n_lead = 1
        # embed/head params run under plain GSPMD outside the manual
        # program; which ones exist depends on the family tree (llama:
        # untied lm_head, no wpe)
        rep = spmd.replicated(self.mesh)
        gp_params: Params = {
            k: jax.device_put(params[k], rep)
            for k in ("wte", "wpe", "ln_f", "lm_head") if k in params
        }
        gp_params["stacked_blocks"] = gpipe.shard_stacked_blocks(
            stacked, self.mesh, config=self.config, n_lead=n_lead)
        opt_state = self.optimizer.init(gp_params)
        return gp_params, opt_state

    def shard_batch(self, ids) -> jnp.ndarray:
        ids = jnp.asarray(ids, dtype=jnp.int32)
        dp = "dp" if "dp" in self.mesh.axis_names else None
        sp = "sp" if "sp" in self.mesh.axis_names else None
        return jax.device_put(ids, NamedSharding(self.mesh, P(dp, sp)))

    def __call__(self, params, opt_state, ids):
        return self._step(params, opt_state, ids)


def decay_mask(params: Params) -> Params:
    """True for leaves that take weight decay: matmul kernels and the
    embedding tables — never biases or LayerNorm scales (GPT-2 recipe).

    Path-based, not ndim-based: stacked block biases are 2-D (``[L, d]``),
    so shape alone cannot distinguish them from kernels.
    """
    def is_decay(path, _leaf) -> bool:
        last = path[-1].key if hasattr(path[-1], "key") else path[-1]
        return last in ("kernel", "wte", "wpe")

    return jax.tree_util.tree_map_with_path(is_decay, params)


def adamw(learning_rate: float = 1e-3, weight_decay: float = 0.01,
          warmup_steps: int = 0, total_steps: Optional[int] = None,
          grad_clip: float = 1.0) -> optax.GradientTransformation:
    """The stock GPT training recipe: AdamW (decay masked off biases and
    LayerNorms, see ``decay_mask``) + global-norm clip, optional linear
    warmup and cosine decay."""
    if total_steps is not None:
        schedule: Any = optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, warmup_steps, total_steps)
    elif warmup_steps:
        schedule = optax.linear_schedule(0.0, learning_rate, warmup_steps)
    else:
        schedule = learning_rate
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, weight_decay=weight_decay, mask=decay_mask),
    )
