"""Model families and the family registry.

Every family module exposes the same pure-function surface —
``init_params`` / ``forward`` / ``forward_with_cache`` / ``make_cache``
over a stacked-block param pytree — so the runtime (decode engine,
speculative decoding, serving, quantization, checkpointing) dispatches on
the config object alone via ``family_module``.
"""

from __future__ import annotations


def family_module(config):
    """Config dataclass -> the model module implementing it.

    MoEConfig subclasses GPT2Config, so it is tested first; LlamaConfig is
    standalone. Plain GPT2Config is the only family the dense pipeline
    partitioner (parallel.partition) can stage.
    """
    from . import gpt2, llama, moe
    if isinstance(config, moe.MoEConfig):
        return moe
    if isinstance(config, llama.LlamaConfig):
        return llama
    if isinstance(config, gpt2.GPT2Config):
        return gpt2
    raise TypeError(f"unknown model config type {type(config).__name__}")


def is_partitionable(config) -> bool:
    """True when the reference's GPT-2 stage-shard WIRE topology applies
    to ``config`` (/forward + /forward_b compat endpoints, remote
    dispatch, shard-pod partial restore) — the wire-parity surface stays
    GPT-2-only by design."""
    from . import gpt2, moe
    return (isinstance(config, gpt2.GPT2Config)
            and not isinstance(config, moe.MoEConfig))


def is_stage_partitionable(config) -> bool:
    """True when ``parallel.partition`` can stage this family's tree —
    THE single staging predicate (engine and serving both consult it).
    Dense GPT-2 and llama stage; MoE's expert tree decodes unstaged."""
    from . import llama
    return is_partitionable(config) or isinstance(config, llama.LlamaConfig)


def is_window_independent(config) -> bool:
    """True when a token's routing/logits do not depend on which other
    tokens share its forward window — the property behind every
    byte-exactness contract that replays tokens in different window
    shapes (speculative verify windows, chunked prefill, prefix-cache
    continuations). MoE capacity-factor routing makes tokens compete for
    expert slots within a window, so it is window-DEPENDENT; the dense
    families are independent."""
    from . import moe
    return not isinstance(config, moe.MoEConfig)
