"""Model families and the family registry.

Every family module exposes the same pure-function surface —
``init_params`` / ``forward`` / ``forward_with_cache`` / ``make_cache``
over a stacked-block param pytree — so the runtime (decode engine,
speculative decoding, serving, quantization, checkpointing) dispatches on
the config object alone via ``family_module``.
"""

from __future__ import annotations


def family_module(config):
    """Config dataclass -> the model module implementing it.

    MoEConfig subclasses GPT2Config, so it is tested first; LlamaConfig is
    standalone. Plain GPT2Config is the only family the dense pipeline
    partitioner (parallel.partition) can stage.
    """
    from . import gpt2, llama, moe
    if isinstance(config, moe.MoEConfig):
        return moe
    if isinstance(config, llama.LlamaConfig):
        return llama
    if isinstance(config, gpt2.GPT2Config):
        return gpt2
    raise TypeError(f"unknown model config type {type(config).__name__}")


def is_partitionable(config) -> bool:
    """True when the dense GPT-2 stage partitioner applies to ``config``."""
    from . import gpt2, moe
    return (isinstance(config, gpt2.GPT2Config)
            and not isinstance(config, moe.MoEConfig))
