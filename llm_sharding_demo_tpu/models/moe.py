"""GPT-2-MoE: the dense MLP swapped for a top-k mixture of experts.

Second model family, and the carrier of *expert parallelism* (the one
mesh axis dense GPT-2 cannot exercise; the reference is dense-only —
SURVEY.md §2.2 "EP: Not applicable"). TPU-first design:

- experts are stacked on their own axis — kernels are
  ``[L, E, d, 4d]`` / ``[L, E, 4d, d]`` — so expert parallelism is a pure
  GSPMD annotation: shard the ``E`` axis over the ``ep`` mesh axis
  (``parallel.spmd.moe_param_pspecs``) and XLA turns the dispatch/combine
  einsums into all-to-alls over ICI;
- routing is the capacity-factor formulation (Shazeer et al. / Switch):
  every shape is static under jit. Per (batch row, expert) each token
  gets a slot index by masked cumsum; tokens past capacity are dropped
  (their combine weight is zero, they ride the residual connection);
- dispatch and combine are one-hot einsums — batched MXU contractions,
  no gather/scatter;
- the router's load-balancing auxiliary loss (mean gate fraction × mean
  assignment fraction × E) is returned alongside logits for the trainer
  to weight.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.layers import gelu_new, linear
from ..ops.attention import KVCache
from .gpt2 import (GPT2Config, Params, _block as gpt2_block, embed,
                   final_logits)


@dataclasses.dataclass(frozen=True)
class MoEConfig(GPT2Config):
    """GPT2Config + router/expert hyperparameters."""

    n_experts: int = 8
    expert_top_k: int = 2
    capacity_factor: float = 1.25

    def __post_init__(self):
        super().__post_init__()
        if not 1 <= self.expert_top_k <= self.n_experts:
            raise ValueError(
                f"expert_top_k={self.expert_top_k} not in "
                f"[1, n_experts={self.n_experts}]")
        if self.attention_impl != "xla":
            # moe.forward hard-codes the XLA attention path; accepting
            # "pallas" here would silently run the wrong kernel
            raise ValueError(
                "MoE blocks support attention_impl='xla' only (the pallas "
                "kernel is wired into the dense model path)")


# Static-analysis/planner contract (tools/graftcheck/costmodel): the
# family's sharding facts — see ``models.gpt2.SHARDING_DESCRIPTOR`` for
# the schema. The expert-axis descriptor: expert-stacked ops shard dim 1
# (the ``E`` axis after the layer axis) over ``ep``, composing with
# Megatron column/row tp WITHIN each expert — the derived tree is pinned
# equal to ``spmd.moe_param_pspecs`` by tests/test_graftplan.py.
# ``ep_divisors``: the ep axis must divide ``n_experts`` (the serving
# EP_DECODE guard).
SHARDING_DESCRIPTOR = {
    "column": ("blocks.attn.c_attn", "blocks.moe.experts.c_fc"),
    "row": ("blocks.attn.c_proj", "blocks.moe.experts.c_proj"),
    "expert": ("blocks.moe.experts.c_fc", "blocks.moe.experts.c_proj"),
    "tp_divisors": ("n_head",),
    "ep_divisors": ("n_experts",),
}


# Numerics contract (tools/graftcheck numerics pass): the two expert
# contractions are the only low-precision arithmetic this module owns
# (everything else delegates to ops/layers.py and ops/quant.py, which
# carry their own contracts). Both follow quant.quant_matmul's
# f32-accumulate / single-final-rounding discipline and ride the same
# seeded ``decode.int8`` tolerance budget — the routed and dense paths
# share these functions, so one declaration covers both.
PRECISION_CONTRACT = {
    "_expert_einsum": {"regime": "carried", "exact": False,
                       "oracle": "decode.int8", "accumulate": "f32",
                       "casts": ("f32", "carried")},
    "_gathered_einsum": {"regime": "carried", "exact": False,
                         "oracle": "decode.int8", "accumulate": "f32",
                         "casts": ("f32", "carried")},
}


def expert_capacity(config: MoEConfig, seq_len: int) -> int:
    """Static per-expert slot count for one batch row."""
    cap = int(config.capacity_factor * config.expert_top_k * seq_len
              / config.n_experts)
    return max(cap, 1)


def init_params(config: MoEConfig, key: jax.Array, dtype=jnp.float32) -> Params:
    """Like gpt2.init_params but with router + stacked experts per block."""
    k_wte, k_wpe, k_attn, k_proj, k_router, k_fc, k_out = jax.random.split(key, 7)
    d, l, e = config.n_embd, config.n_layer, config.n_experts
    std = 0.02

    def normal(k, shape):
        return (jax.random.normal(k, shape) * std).astype(dtype)

    return {
        "wte": normal(k_wte, (config.vocab_size, d)),
        "wpe": normal(k_wpe, (config.n_positions, d)),
        "blocks": {
            "ln_1": {"scale": jnp.ones((l, d), dtype), "bias": jnp.zeros((l, d), dtype)},
            "attn": {
                "c_attn": {"kernel": normal(k_attn, (l, d, 3 * d)),
                           "bias": jnp.zeros((l, 3 * d), dtype)},
                "c_proj": {"kernel": normal(k_proj, (l, d, d)),
                           "bias": jnp.zeros((l, d), dtype)},
            },
            "ln_2": {"scale": jnp.ones((l, d), dtype), "bias": jnp.zeros((l, d), dtype)},
            "moe": {
                "router": {"kernel": normal(k_router, (l, d, e))},
                "experts": {
                    "c_fc": {"kernel": normal(k_fc, (l, e, d, 4 * d)),
                             "bias": jnp.zeros((l, e, 4 * d), dtype)},
                    "c_proj": {"kernel": normal(k_out, (l, e, 4 * d, d)),
                               "bias": jnp.zeros((l, e, d), dtype)},
                },
            },
        },
        "ln_f": {"scale": jnp.ones((d,), dtype), "bias": jnp.zeros((d,), dtype)},
    }


def _expert_einsum(eq: str, x: jnp.ndarray, kernel) -> jnp.ndarray:
    """Batched-over-experts contraction, int8-aware.

    A quantized expert kernel is a ``QuantizedTensor`` with ``q`` int8
    [E, in, out] and per-(expert, out-channel) ``scale`` [E, out]; the
    int8->activation convert sits on the dot operand and the rescale
    broadcasts over the [E, ..., out] result.

    Deliberately the XLA lowering, NOT a Pallas kernel: measured on the
    bench chip at the 8-expert/124M geometry, the expert-batched einsum
    decodes at ~975 tok/s vs ~755 for a grid=(E, out_blocks) Pallas
    kernel (1-row tiles pay per-cell overhead XLA's batched matmul
    avoids) and ~595 for per-expert unrolled kernel launches. The dense
    model's matvecs are where the custom kernel wins (see
    quant.quant_matmul); here XLA already streams the batch well.
    """
    from ..ops import quant

    if quant.is_quantized(kernel):
        lead = x.shape[1:-1]
        e, _, out = kernel.q.shape
        # f32 accumulation + ONE final rounding to the activation dtype
        # — the quant.quant_matmul discipline. The bf16 form previously
        # accumulated at bf16 and rounded twice (dot, then rescale); the
        # numerics pass's unstable-reduction rule flags that shape. f32
        # activations are unchanged bit-for-bit.
        y = jnp.einsum(eq, x, kernel.q.astype(x.dtype),
                       preferred_element_type=jnp.float32)
        scale = kernel.scale.reshape((e,) + (1,) * len(lead) + (out,))
        return (y * scale.astype(jnp.float32)).astype(x.dtype)
    return jnp.einsum(eq, x, kernel)


def _gather_expert(kernel, idx: jnp.ndarray):
    """Select expert slices from a stacked ``[E, in, out]`` kernel by
    token: ``idx`` [N] -> [N, in, out]. int8-aware: a ``QuantizedTensor``
    gathers its codes and per-(expert, channel) scales in lockstep."""
    from ..ops import quant

    if quant.is_quantized(kernel):
        return quant.QuantizedTensor(jnp.take(kernel.q, idx, axis=0),
                                     jnp.take(kernel.scale, idx, axis=0))
    return jnp.take(kernel, idx, axis=0)


def _gathered_einsum(x: jnp.ndarray, kernel) -> jnp.ndarray:
    """[N, in] x per-token gathered [N, in, out] -> [N, out] (int8-aware:
    same dequant-after-dot math as ``_expert_einsum``, so routed and
    dense paths agree bitwise on the same expert)."""
    from ..ops import quant

    if quant.is_quantized(kernel):
        y = jnp.einsum("nd,ndf->nf", x, kernel.q.astype(x.dtype),
                       preferred_element_type=jnp.float32)
        return (y * kernel.scale.astype(jnp.float32)).astype(x.dtype)
    return jnp.einsum("nd,ndf->nf", x, kernel)


def _topk_gates(gates: jnp.ndarray, e: int, k: int,
                token_valid: Optional[jnp.ndarray] = None):
    """THE top-k selection: iteratively take the argmax, zero it, repeat.
    Returns ``(idxs [k x (B,S)], onehots [k x (B,S,E)], w [k,B,S])`` with
    ``w`` renormalized to sum to 1 per token. One definition shared by
    the dense dispatch path and the routed decode path — their bitwise
    routing/combine-weight agreement (the dispatch contract in
    ``_moe_block``) depends on the selection logic being literally the
    same code."""
    sel_gates = gates
    idxs, onehots, weights = [], [], []
    for _ in range(k):
        idx = jnp.argmax(sel_gates, axis=-1)                    # [B,S]
        oh = jax.nn.one_hot(idx, e, dtype=gates.dtype)          # [B,S,E]
        if token_valid is not None:
            oh = oh * token_valid[..., None]
        idxs.append(idx)
        onehots.append(oh)
        weights.append(jnp.sum(sel_gates * oh, axis=-1))        # [B,S]
        sel_gates = sel_gates * (1.0 - oh)
    w = jnp.stack(weights)                                      # [k,B,S]
    w = w / jnp.maximum(jnp.sum(w, axis=0, keepdims=True), 1e-9)
    return idxs, onehots, w


def moe_mlp_routed(moe_params: Params, h: jnp.ndarray, config: MoEConfig,
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Routed-gather expert MLP for DECODE shapes: gather only the top-k
    selected experts' kernels per token (``jnp.take`` over the stacked
    ``[E, ...]`` axis) instead of contracting the full expert stack.

    The dense dispatch-tensor formulation (``moe_mlp``) streams ALL E
    experts' weights every step to use k of them — for top-2-of-8
    single-token decode that is 4x the necessary MLP weight traffic, and
    the MLP is ~7/8 of this family's weights (VERDICT r2 weak #2). At
    ``S == 1`` capacity can never bind (each expert grants >= 1 slot per
    row and a token takes at most one slot per expert), so routing,
    combine weights, and outputs are EXACTLY the dense path's — pinned
    bitwise by tests/test_moe.py. The engine dispatches here for
    single-token steps when ``B * k <= E`` (beyond that the dense batched
    contraction streams less).
    """
    b, s, d = h.shape
    e, k = config.n_experts, config.expert_top_k
    experts = moe_params["experts"]

    gate_logits = linear(h, moe_params["router"]["kernel"])     # [B,S,E]
    gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    idxs, onehots, w = _topk_gates(gates, e, k)

    hf = h.reshape(b * s, d)
    out = jnp.zeros_like(hf)
    for i in range(k):
        idx_f = idxs[i].reshape(b * s)
        h1 = _gathered_einsum(hf, _gather_expert(
            experts["c_fc"]["kernel"], idx_f))
        h1 = gelu_new(h1 + jnp.take(experts["c_fc"]["bias"], idx_f, axis=0))
        h2 = _gathered_einsum(h1, _gather_expert(
            experts["c_proj"]["kernel"], idx_f))
        h2 = h2 + jnp.take(experts["c_proj"]["bias"], idx_f, axis=0)
        out = out + w[i].reshape(b * s, 1).astype(h.dtype) * h2

    # same aux-loss formula as the dense path (a training quantity;
    # decode callers drop it)
    aux = jnp.sum(jnp.mean(onehots[0], axis=(0, 1))
                  * jnp.mean(gates, axis=(0, 1))) * e
    return out.reshape(b, s, d), aux


def moe_mlp(moe_params: Params, h: jnp.ndarray, config: MoEConfig,
            token_valid: Optional[jnp.ndarray] = None,
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k routed expert MLP. [B, S, d] -> ([B, S, d], aux_loss scalar).

    ``token_valid`` ([B, S] bool, optional): tokens marked False (left-pad
    columns of a ragged batch) are excluded from routing entirely — zero
    combine weight AND zero dispatch, so they cannot consume per-expert
    capacity slots that real tokens need. Their output rows are zero (the
    residual carries them; nothing downstream reads pad positions).
    """
    b, s, d = h.shape
    e, k = config.n_experts, config.expert_top_k
    cap = expert_capacity(config, s)

    # via ops.layers.linear so the weight-only-int8 router leaf works too
    # (E is rarely lane-aligned, so the router usually takes the XLA
    # path — it is a negligible fraction of the weight bytes)
    gate_logits = linear(h, moe_params["router"]["kernel"])     # [B,S,E]
    gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    # shared top-k selection (one definition, see _topk_gates); the
    # renormalized w makes combine weights sum to 1 per token
    _, onehots, w = _topk_gates(gates, e, k, token_valid)
    sel = jnp.stack(onehots)                                    # [k,B,S,E]

    # slot assignment: serialize the k choices along the sequence so the
    # cumsum hands out distinct slots; position = (# prior assignments to
    # that expert) per batch row
    sel_flat = sel.transpose(1, 0, 2, 3).reshape(b, k * s, e)   # [B,k*S,E]
    pos = jnp.cumsum(sel_flat, axis=1) - 1.0                    # [B,k*S,E]
    keep = (pos < cap) & (sel_flat > 0)
    slot = jnp.where(keep, pos, 0).astype(jnp.int32)
    slot_oh = jax.nn.one_hot(slot, cap, dtype=gates.dtype) * keep[..., None]
    # dispatch tensor [B, k*S, E, C] -> fold k back out and sum the k
    # one-hots per token (a token never picks the same expert twice).
    # The merged axis is k-MAJOR (sel_flat came from [B, k, S, E]), so it
    # un-flattens as (k, s) — (s, k) would scramble token identities.
    dispatch = slot_oh.reshape(b, k, s, e, cap).transpose(1, 0, 2, 3, 4)
    combine = jnp.einsum("kbs,kbsec->bsec", w, dispatch)        # [B,S,E,C]
    dispatch = jnp.sum(dispatch, axis=0)                        # [B,S,E,C]

    # expert compute: everything below is batched over E (the ep axis)
    xin = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(h.dtype), h)
    h1 = _expert_einsum("ebcd,edf->ebcf", xin,
                        moe_params["experts"]["c_fc"]["kernel"])
    h1 = gelu_new(h1 + moe_params["experts"]["c_fc"]["bias"][:, None, None, :])
    h2 = _expert_einsum("ebcf,efd->ebcd", h1,
                        moe_params["experts"]["c_proj"]["kernel"])
    h2 = h2 + moe_params["experts"]["c_proj"]["bias"][:, None, None, :]
    out = jnp.einsum("bsec,ebcd->bsd", combine.astype(h.dtype), h2)

    # Switch-style load-balance loss over the top-1 assignment
    frac_tokens = jnp.mean(sel[0], axis=(0, 1))                 # [E]
    frac_gates = jnp.mean(gates, axis=(0, 1))                   # [E]
    aux = jnp.sum(frac_tokens * frac_gates) * e
    return out, aux


def _moe_block(layer_params: Params, h: jnp.ndarray, config: MoEConfig,
               cache_k: Optional[jnp.ndarray], cache_v: Optional[jnp.ndarray],
               offset, k_valid_from: Optional[jnp.ndarray] = None,
               layer_idx=None, decode_kernel: Optional[str] = None,
               routed_mlp: bool = True,
               ) -> Tuple[jnp.ndarray, jnp.ndarray,
                          Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    """One pre-LN MoE block, optionally reading/writing the KV cache
    (full stacked buffers + ``layer_idx``, the in-place carry pattern —
    see ``ops.attention.write_kv_layer``).

    Delegates the attention half to ``gpt2._block`` (one implementation
    serves both families) with the dense MLP swapped for ``moe_mlp`` via
    ``mlp_fn``. Returns ``(h, aux_loss, new_ck, new_cv)``.

    With left-padded ragged batches (``k_valid_from``), the pad columns'
    garbage embeddings are excluded from routing (``token_valid``): a pad
    token sitting at sequence start would otherwise win capacity slots in
    the masked-cumsum race and evict real tokens to the residual path.
    """
    if k_valid_from is None:
        token_valid = None
    else:
        s = h.shape[1]
        token_valid = ((offset + jnp.arange(s))[None, :]
                       >= k_valid_from[:, None])            # [B, S]
    aux_cell = []
    # Routed-gather dispatch (static): single-token steps with few enough
    # rows gather only the selected experts' kernels (k/E of the MLP
    # weight traffic — see moe_mlp_routed). Decode tokens are always real
    # (pad lives in the prefix), so token_valid never gates them.
    # ``routed_mlp=False`` (ep-sharded inference) keeps the dense
    # formulation, whose einsums GSPMD partitions over the expert axis.
    use_routed = (routed_mlp and h.shape[1] == 1
                  and h.shape[0] * config.expert_top_k <= config.n_experts)

    def mlp_fn(block_params: Params, m: jnp.ndarray) -> jnp.ndarray:
        if use_routed:
            out, aux = moe_mlp_routed(block_params["moe"], m, config)
        else:
            out, aux = moe_mlp(block_params["moe"], m, config, token_valid)
        aux_cell.append(aux)
        return out

    h, new_ck, new_cv = gpt2_block(
        layer_params, h, config.n_head, config.layer_norm_epsilon,
        cache_k, cache_v, offset, k_valid_from=k_valid_from, mlp_fn=mlp_fn,
        layer_idx=layer_idx, decode_kernel=decode_kernel)
    return h, aux_cell[0], new_ck, new_cv


def forward(params: Params, input_ids: jnp.ndarray, config: MoEConfig,
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[B, S] -> ([B, S, vocab] logits, summed router aux loss)."""
    h = embed(params, input_ids, 0)

    def body(carry, layer_params):
        h, aux = carry
        h, layer_aux, _, _ = _moe_block(layer_params, h, config, None, None, 0)
        return (h, aux + layer_aux), None

    (h, aux), _ = jax.lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                               params["blocks"])
    return final_logits(params, h, config.layer_norm_epsilon), aux


def forward_with_cache(params: Params, input_ids: jnp.ndarray,
                       config: MoEConfig, cache: KVCache,
                       pad: Optional[jnp.ndarray] = None,
                       flash_prefill: bool = False,
                       decode_kernel: Optional[str] = None,
                       routed_mlp: bool = True,
                       ) -> Tuple[jnp.ndarray, KVCache]:
    """Cached MoE forward (prefill / incremental decode), engine-compatible.

    Same contract as ``gpt2.forward_with_cache`` so ``runtime.engine.
    DecodeEngine`` can drive an MoE model unchanged; the router aux loss is
    a training quantity and is dropped here (XLA dead-code-eliminates it).

    Routing semantics under the capacity formulation: a *full-sequence*
    forward makes tokens compete for per-expert slots (the cumsum in
    ``moe_mlp``), so its outputs are sequence-dependent when capacity
    binds. A single-token decode step routes one token against a fresh
    capacity of ``max(int(cf·k/E), 1) >= 1`` slot per expert, so decode
    NEVER drops. Cached decode therefore agrees exactly with the uncached
    full re-forward iff prefill capacity doesn't bind (e.g.
    ``capacity_factor >= n_experts / expert_top_k``); with binding capacity
    decode is the *better-quality* path (no drops), not a divergence bug.
    """
    if flash_prefill:
        # engine-API uniformity only: MoEConfig enforces attention_impl
        # 'xla' (its routed MLP is the novelty, not the attention), so the
        # engine can never derive a True flag for this family
        raise NotImplementedError(
            "flash prefill covers the dense families; MoEConfig enforces "
            "attention_impl='xla'")
    if pad is None:
        h = embed(params, input_ids, cache.length)
        k_valid_from = None
    else:
        h = embed(params, input_ids, cache.length - pad[:, None])
        k_valid_from = pad
    offset = cache.length

    def body(carry, xs):
        h, K, V = carry
        layer_params, li = xs
        out, _, K, V = _moe_block(layer_params, h, config, K, V, offset,
                                  k_valid_from, layer_idx=li,
                                  decode_kernel=decode_kernel,
                                  routed_mlp=routed_mlp)
        return (out, K, V), None

    (h, new_k, new_v), _ = jax.lax.scan(
        body, (h, cache.k, cache.v),
        (params["blocks"], jnp.arange(config.n_layer)))
    new_len = cache.length + jnp.asarray(h.shape[1], dtype=jnp.int32)
    cache = KVCache(k=new_k, v=new_v, length=new_len)
    return final_logits(params, h, config.layer_norm_epsilon), cache


def make_cache(config: MoEConfig, batch: int, max_seq: int,
               dtype=jnp.float32) -> KVCache:
    """KV cache for the MoE model (attention is dense GPT-2 attention)."""
    if max_seq > config.n_positions:
        raise ValueError(
            f"max_seq={max_seq} exceeds n_positions={config.n_positions}; "
            "decode past the position table would silently clamp")
    return KVCache.create(config.n_layer, batch, config.n_head, max_seq,
                         config.head_dim, dtype)
