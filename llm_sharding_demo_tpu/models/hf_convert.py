"""HuggingFace GPT-2 checkpoint -> JAX param-pytree converter.

The reference downloads full HF weights into *every* pod at import time
(reference server.py:40-42) and never saves anything (SURVEY.md §5
"Checkpoint / resume"). Here conversion is a one-time, explicit step; the
result is a plain pytree that pipeline stages can slice so each device holds
only its own blocks.

Layout notes (the Conv1D trap, SURVEY.md §7 hard part (b)): HF GPT-2 uses
``Conv1D`` whose ``weight`` is stored ``[in_features, out_features]`` — the
transpose of ``nn.Linear``. Our kernels use the same ``[in, out]`` layout
(ops.layers.linear), so attention/MLP weights are copied as-is with no
transpose; only awareness is required, not surgery. The LM head is tied to
``wte`` in GPT-2 (HF ``tie_word_embeddings``), so no separate head tensor is
converted.

torch is imported lazily: it is only needed when actually converting, never
on the TPU serving path.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from .gpt2 import GPT2Config, Params


def config_from_hf(hf_config: Any) -> GPT2Config:
    """Map an HF ``GPT2Config`` to ours (fields used by the compute path).

    Rejects checkpoints whose semantics our forward does not implement —
    silent wrong logits are worse than a loud error.
    """
    if not getattr(hf_config, "tie_word_embeddings", True):
        raise ValueError(
            "untied lm_head is not supported: final_logits ties the head to "
            "wte (GPT-2's actual weight sharing)")
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act != "gelu_new":
        raise ValueError(
            f"activation_function={act!r} not supported; forward hard-wires "
            "gelu_new (ops.layers.gelu_new)")
    # Attention-math variants our kernel does not implement: it always
    # scales by 1/sqrt(head_dim) and never rescales by layer index.
    if not getattr(hf_config, "scale_attn_weights", True):
        raise ValueError("scale_attn_weights=False not supported: "
                         "causal_attention always scales by 1/sqrt(head_dim)")
    if getattr(hf_config, "scale_attn_by_inverse_layer_idx", False):
        raise ValueError("scale_attn_by_inverse_layer_idx=True not supported")
    if getattr(hf_config, "reorder_and_upcast_attn", False):
        raise ValueError("reorder_and_upcast_attn=True not supported")
    return GPT2Config(
        vocab_size=hf_config.vocab_size,
        n_positions=hf_config.n_positions,
        n_embd=hf_config.n_embd,
        n_layer=hf_config.n_layer,
        n_head=hf_config.n_head,
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
    )


def params_from_state_dict(state_dict: Dict[str, Any], config: GPT2Config,
                           dtype=jnp.float32) -> Params:
    """Convert a torch ``GPT2LMHeadModel.state_dict()`` into our pytree.

    Blocks are stacked on a leading layer axis (models.gpt2 docstring).
    Buffers like ``attn.bias`` (HF's causal-mask triangle) are ignored — the
    mask is computed, not stored, on our side.
    """

    def get(name: str) -> np.ndarray:
        t = state_dict[name]
        # torch tensors expose .detach().cpu().numpy(); accept ndarrays too
        # so tests can feed pre-extracted dicts.
        if hasattr(t, "detach"):
            t = t.detach().cpu().numpy()
        return np.asarray(t)

    def stack(fmt: str) -> jnp.ndarray:
        return jnp.asarray(
            np.stack([get(fmt.format(i)) for i in range(config.n_layer)]),
            dtype=dtype)

    params: Params = {
        "wte": jnp.asarray(get("transformer.wte.weight"), dtype=dtype),
        "wpe": jnp.asarray(get("transformer.wpe.weight"), dtype=dtype),
        "blocks": {
            "ln_1": {"scale": stack("transformer.h.{}.ln_1.weight"),
                     "bias": stack("transformer.h.{}.ln_1.bias")},
            "attn": {
                "c_attn": {"kernel": stack("transformer.h.{}.attn.c_attn.weight"),
                           "bias": stack("transformer.h.{}.attn.c_attn.bias")},
                "c_proj": {"kernel": stack("transformer.h.{}.attn.c_proj.weight"),
                           "bias": stack("transformer.h.{}.attn.c_proj.bias")},
            },
            "ln_2": {"scale": stack("transformer.h.{}.ln_2.weight"),
                     "bias": stack("transformer.h.{}.ln_2.bias")},
            "mlp": {
                "c_fc": {"kernel": stack("transformer.h.{}.mlp.c_fc.weight"),
                         "bias": stack("transformer.h.{}.mlp.c_fc.bias")},
                "c_proj": {"kernel": stack("transformer.h.{}.mlp.c_proj.weight"),
                           "bias": stack("transformer.h.{}.mlp.c_proj.bias")},
            },
        },
        "ln_f": {"scale": jnp.asarray(get("transformer.ln_f.weight"), dtype=dtype),
                 "bias": jnp.asarray(get("transformer.ln_f.bias"), dtype=dtype)},
    }
    return params


def params_from_hf_model(model: Any, dtype=jnp.float32):
    """Convenience: torch ``GPT2LMHeadModel`` instance -> (config, params)."""
    config = config_from_hf(model.config)
    return config, params_from_state_dict(model.state_dict(), config, dtype=dtype)


# ---------------------------------------------------------------------------
# LLaMA family. Unlike GPT-2's Conv1D, HF llama uses ``nn.Linear`` whose
# weight is stored ``[out_features, in_features]`` — every matmul weight
# below is TRANSPOSED into our [in, out] kernel layout.
# ---------------------------------------------------------------------------

def llama_config_from_hf(hf_config: Any) -> "Any":
    """Map an HF ``LlamaConfig`` to ours; reject unimplemented semantics."""
    from .llama import LlamaConfig

    if getattr(hf_config, "tie_word_embeddings", False):
        raise ValueError("tied llama embeddings not supported: the family "
                         "converts a separate lm_head tensor")
    act = getattr(hf_config, "hidden_act", "silu")
    if act != "silu":
        raise ValueError(f"hidden_act={act!r} not supported; the SwiGLU MLP "
                         "hard-wires silu")
    if getattr(hf_config, "rope_scaling", None):
        raise ValueError("rope_scaling not supported: ops.rope implements "
                         "plain RoPE only")
    if getattr(hf_config, "attention_bias", False):
        raise ValueError("attention_bias=True not supported: llama kernels "
                         "are bias-free")
    hd = getattr(hf_config, "head_dim", None)
    derived = hf_config.hidden_size // hf_config.num_attention_heads
    if hd is not None and hd != derived:
        raise ValueError(f"explicit head_dim={hd} != hidden/heads={derived} "
                         "not supported")
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        n_positions=hf_config.max_position_embeddings,
        n_embd=hf_config.hidden_size,
        n_layer=hf_config.num_hidden_layers,
        n_head=hf_config.num_attention_heads,
        n_kv_head=getattr(hf_config, "num_key_value_heads",
                          hf_config.num_attention_heads),
        intermediate_size=hf_config.intermediate_size,
        rms_norm_eps=hf_config.rms_norm_eps,
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
    )


def llama_params_from_state_dict(state_dict: Dict[str, Any], config: Any,
                                 dtype=jnp.float32) -> Params:
    """Convert a torch ``LlamaForCausalLM.state_dict()`` into our pytree."""

    def get_t(name: str) -> np.ndarray:
        t = state_dict[name]
        if hasattr(t, "detach"):
            t = t.detach().cpu().numpy()
        return np.asarray(t).T          # nn.Linear [out, in] -> [in, out]

    def get(name: str) -> np.ndarray:
        t = state_dict[name]
        if hasattr(t, "detach"):
            t = t.detach().cpu().numpy()
        return np.asarray(t)

    def stack_t(fmt: str) -> jnp.ndarray:
        return jnp.asarray(
            np.stack([get_t(fmt.format(i)) for i in range(config.n_layer)]),
            dtype=dtype)

    def stack(fmt: str) -> jnp.ndarray:
        return jnp.asarray(
            np.stack([get(fmt.format(i)) for i in range(config.n_layer)]),
            dtype=dtype)

    L = "model.layers.{}."
    return {
        "wte": jnp.asarray(get("model.embed_tokens.weight"), dtype=dtype),
        "blocks": {
            "ln_attn": {"scale": stack(L + "input_layernorm.weight")},
            "attn": {
                "wq": {"kernel": stack_t(L + "self_attn.q_proj.weight")},
                "wk": {"kernel": stack_t(L + "self_attn.k_proj.weight")},
                "wv": {"kernel": stack_t(L + "self_attn.v_proj.weight")},
                "wo": {"kernel": stack_t(L + "self_attn.o_proj.weight")},
            },
            "ln_mlp": {"scale": stack(L + "post_attention_layernorm.weight")},
            "mlp": {
                "gate": {"kernel": stack_t(L + "mlp.gate_proj.weight")},
                "up": {"kernel": stack_t(L + "mlp.up_proj.weight")},
                "down": {"kernel": stack_t(L + "mlp.down_proj.weight")},
            },
        },
        "ln_f": {"scale": jnp.asarray(get("model.norm.weight"), dtype=dtype)},
        "lm_head": {"kernel": jnp.asarray(get_t("lm_head.weight"),
                                          dtype=dtype)},
    }


def llama_params_from_hf_model(model: Any, dtype=jnp.float32):
    """torch ``LlamaForCausalLM`` instance -> (config, params)."""
    config = llama_config_from_hf(model.config)
    return config, llama_params_from_state_dict(model.state_dict(), config,
                                                dtype=dtype)
