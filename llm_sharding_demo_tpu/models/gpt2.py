"""GPT-2 as pure JAX functions over a parameter pytree.

TPU-native re-design of the model layer the reference gets from HuggingFace
(``AutoModelForCausalLM.from_pretrained`` at reference server.py:41, torch
modules ``wte/wpe/drop/h/ln_f/lm_head`` wired into two shards at
server.py:56-60). Differences by design, not translation:

- Parameters are a plain pytree (nested dicts of ``jnp`` arrays). All
  transformer blocks are *stacked on a leading layer axis*, so applying a
  stage's blocks is one ``lax.scan`` — a single compiled loop body reused
  across layers — instead of the reference's Python ``for block in
  self.blocks`` (server.py:84-85, 99-100).
- The LM head is weight-tied to ``wte`` (as in GPT-2 proper): logits are
  ``h @ wte.T``. No separate lm_head tensor exists, which also fixes the
  reference quirk of every role holding full weights (server.py:108-110).
- Kernels use the ``[in, out]`` layout matching HF ``Conv1D`` storage so the
  checkpoint converter (``models.hf_convert``) is copy-only.
- Everything is shape-static and jit-friendly; positions derive from an
  integer offset rather than re-materialized ``arange(0, seq_len)`` per call
  (the reference recomputes positions from zero every token,
  server.py:80, because it has no cache).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import (KVCache, cached_attention_inplace,
                             causal_attention, merge_heads, split_heads,
                             write_kv_layer)
from ..ops.layers import gelu_new, layer_norm, linear

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    """Architecture hyperparameters (mirrors HF ``GPT2Config`` fields we use)."""

    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5
    # "xla": fused einsum attention (default). "pallas": Mosaic flash
    # kernel (ops.flash_attention). "ring": sequence-parallel ring
    # attention over the mesh's "sp" axis (ops.ring_attention) — the
    # long-context path; requires a mesh passed to ``forward``. All three
    # apply to the no-cache forward (training / compat endpoints).
    # Cached single-token decode has its own dispatch, independent of
    # this knob: the engine's ``decode_kernel`` routes it through the
    # Pallas flash-decode kernel (ops.decode_attention) on TPU, or the
    # fused XLA path in the byte-pinned parity modes.
    attention_impl: str = "xla"

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    def __post_init__(self):
        if self.n_embd % self.n_head != 0:
            raise ValueError(
                f"n_embd={self.n_embd} not divisible by n_head={self.n_head}")
        if self.attention_impl not in ("xla", "pallas", "ring"):
            raise ValueError(
                f"attention_impl={self.attention_impl!r} not xla|pallas|ring")


# Static-analysis/planner contract (tools/graftcheck/costmodel): how this
# family's stacked param tree shards, as architectural facts rather than
# hand-written PartitionSpecs. ``column``/``row`` name the ops (kernel +
# optional bias siblings) that are Megatron column-/row-parallel over a
# ``tp`` axis; ``expert`` names ops stacked on an expert axis (dim 1 of
# the block leaf, after the layer axis) shardable over ``ep``;
# ``tp_divisors``/``ep_divisors`` name config fields the corresponding
# mesh axis size must divide for the plan to be runnable (the engine's
# own guards). ``costmodel.derive_pspecs`` turns this into the full
# PartitionSpec tree — pinned equal to the hand-tuned ``spmd``
# layouts by tests/test_graftplan.py.
SHARDING_DESCRIPTOR = {
    "column": ("blocks.attn.c_attn", "blocks.mlp.c_fc"),
    "row": ("blocks.attn.c_proj", "blocks.mlp.c_proj"),
    "expert": (),
    "tp_divisors": ("n_head",),
    "ep_divisors": (),
    # MHA: kv heads == n_head, so a kvp (KV-partition) axis shards the
    # same head count tp does (tools/graftcheck placement/costmodel)
    "kvp_divisors": ("n_head",),
}


# Named configs for the BASELINE.json measurement matrix. "tiny-gpt2" matches
# sshleifer/tiny-gpt2 (the reference's default MODEL_ID, server.py:20);
# "gpt2" is GPT-2 124M; "gpt2-medium" the 355M config (4-stage target).
CONFIGS: Dict[str, GPT2Config] = {
    "tiny-gpt2": GPT2Config(vocab_size=50257, n_positions=1024, n_embd=2,
                            n_layer=2, n_head=2),
    "gpt2": GPT2Config(vocab_size=50257, n_positions=1024, n_embd=768,
                       n_layer=12, n_head=12),
    "gpt2-medium": GPT2Config(vocab_size=50257, n_positions=1024, n_embd=1024,
                              n_layer=24, n_head=16),
}


def init_params(config: GPT2Config, key: jax.Array,
                dtype=jnp.float32) -> Params:
    """Random-init parameters (normal(0.02) weights, zero biases, unit LN).

    Block tensors carry a leading ``n_layer`` axis (see module docstring).
    """
    k_wte, k_wpe, k_blocks = jax.random.split(key, 3)
    d, l = config.n_embd, config.n_layer
    std = 0.02

    def normal(k, shape):
        return (jax.random.normal(k, shape) * std).astype(dtype)

    bkeys = jax.random.split(k_blocks, 4)
    params: Params = {
        "wte": normal(k_wte, (config.vocab_size, d)),
        "wpe": normal(k_wpe, (config.n_positions, d)),
        "blocks": {
            "ln_1": {"scale": jnp.ones((l, d), dtype), "bias": jnp.zeros((l, d), dtype)},
            "attn": {
                "c_attn": {"kernel": normal(bkeys[0], (l, d, 3 * d)),
                           "bias": jnp.zeros((l, 3 * d), dtype)},
                "c_proj": {"kernel": normal(bkeys[1], (l, d, d)),
                           "bias": jnp.zeros((l, d), dtype)},
            },
            "ln_2": {"scale": jnp.ones((l, d), dtype), "bias": jnp.zeros((l, d), dtype)},
            "mlp": {
                "c_fc": {"kernel": normal(bkeys[2], (l, d, 4 * d)),
                         "bias": jnp.zeros((l, 4 * d), dtype)},
                "c_proj": {"kernel": normal(bkeys[3], (l, 4 * d, d)),
                           "bias": jnp.zeros((l, d), dtype)},
            },
        },
        "ln_f": {"scale": jnp.ones((d,), dtype), "bias": jnp.zeros((d,), dtype)},
    }
    return params


# ---------------------------------------------------------------------------
# Forward pieces. Split into embed / blocks / final so the pipeline
# partitioner (parallel.partition) can hand each stage exactly the pieces the
# reference gives its shards: A = wte+wpe+blocks[:k] (server.py:68-86),
# B = blocks[k:]+ln_f+lm_head (server.py:90-103) — generalized to N stages.
# ---------------------------------------------------------------------------

def embed(params: Params, input_ids: jnp.ndarray,
          position_offset: jnp.ndarray | int = 0) -> jnp.ndarray:
    """Token + position embeddings. [B, S] int32 -> [B, S, D].

    ``position_offset`` is the absolute position of the first token (nonzero
    during incremental decode). The reference always uses offset 0 because it
    re-forwards the full sequence (server.py:80). A ``[B, 1]`` offset gives
    per-row positions for left-padded ragged batches (pad columns clip to
    position 0; their outputs are never read — attention masks them as keys
    and sampling reads only the final, real column).
    """
    seq_len = input_ids.shape[-1]
    positions = jnp.maximum(position_offset + jnp.arange(seq_len), 0)
    wte = params["wte"]
    from ..ops.quant import is_quantized
    if is_quantized(wte):  # weight-only int8 table (ops.quant)
        from ..ops.quant import embed_rows
        return embed_rows(wte, input_ids) + params["wpe"][positions]
    return wte[input_ids] + params["wpe"][positions]


def _block(block_params: Params, h: jnp.ndarray, n_head: int, eps: float,
           cache_k: Optional[jnp.ndarray], cache_v: Optional[jnp.ndarray],
           offset, attn_impl: str = "xla",
           k_valid_from: Optional[jnp.ndarray] = None, mesh=None,
           mlp_fn=None, flash_prefill: bool = False, layer_idx=None,
           decode_kernel: Optional[str] = None,
           ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray]]:
    """One pre-LN transformer block; optionally reads/writes the KV cache.

    ``cache_k``/``cache_v`` (when given) are the FULL stacked
    ``[L, B, H, max_seq, hd]`` buffers and ``layer_idx`` selects this
    block's slice: the write is an in-place token-column
    ``dynamic_update_slice`` on the loop-carried cache (see
    ``ops.attention.write_kv_layer`` for why slice-per-layer re-stacking
    was a full cache copy per decode step). Returns the updated stacks.

    ``mlp_fn(block_params, m) -> mlp_out`` swaps the dense MLP for another
    feed-forward (``models.moe`` passes its routed expert MLP here), so the
    attention half — the part every family shares — exists exactly once.

    ``flash_prefill`` (static) routes the CACHED path's attention through
    the Pallas flash kernel. Callers may set it only for a fresh-cache
    prefill (offset 0, no pad, S == full window): there the cached
    attention is exactly plain causal attention over the new K/V, so the
    cache write and the attention decouple — the kernel never touches the
    cache buffers and the O(S^2) score materialization disappears at
    long context (the engine derives the flag, runtime.engine._prefill).
    """
    a = layer_norm(h, block_params["ln_1"]["scale"], block_params["ln_1"]["bias"], eps)
    qkv = linear(a, block_params["attn"]["c_attn"]["kernel"],
                 block_params["attn"]["c_attn"]["bias"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q, k, v = (split_heads(x, n_head) for x in (q, k, v))
    if cache_k is None:
        if attn_impl == "pallas":
            from ..ops.flash_attention import (flash_attention,
                                               flash_profitable)
            if flash_profitable(q.shape[2]):
                attn_out = flash_attention(
                    q, k, v, interpret=jax.default_backend() != "tpu")
            else:
                # below the measured crossover the XLA einsum wins —
                # "pallas" means "kernel where it pays", never a regression
                attn_out = causal_attention(q, k, v, q_offset=offset,
                                            k_valid_from=k_valid_from)
        elif attn_impl == "ring":
            from ..ops.ring_attention import ring_attention  # lazy import
            if mesh is None:
                raise ValueError(
                    "attention_impl='ring' needs a mesh with an 'sp' axis: "
                    "pass forward(..., mesh=mesh) (or TrainStep(mesh=...))")
            if k_valid_from is not None:
                raise NotImplementedError(
                    "ring attention does not support ragged (left-padded) "
                    "batches")
            attn_out = ring_attention(q, k, v, mesh, axis="sp")
        else:
            attn_out = causal_attention(q, k, v, q_offset=offset,
                                        k_valid_from=k_valid_from)
        new_ck = new_cv = None
    elif decode_kernel:
        # FUSED cache mode (see ops.attention.create_fused_cache):
        # ``cache_k`` is the [L, B, H, Smax, 2*hd] fused buffer and
        # ``cache_v`` an empty placeholder riding the pytree.
        from ..ops.attention import (cached_attention_fused,
                                     write_kv_layer_fused)
        if flash_prefill:
            from ..ops.flash_attention import flash_attention
            new_ck = write_kv_layer_fused(cache_k, k, v, layer_idx, offset)
            attn_out = flash_attention(
                q, k, v, interpret=jax.default_backend() != "tpu")
        elif q.shape[2] == 1:
            # single-token step -> the Pallas flash-decode kernel: fused
            # row written in place inside the kernel, KV blocks streamed
            # with a depth-adaptive trip count (ops.decode_attention —
            # the XLA path measures ~3x slower at batched-decode shapes)
            from ..ops.decode_attention import decode_attention
            attn_out, new_ck = decode_attention(
                q, k, v, cache_k, layer_idx, offset, k_valid_from,
                interpret=decode_kernel == "interpret")
        else:
            attn_out, new_ck = cached_attention_fused(
                q, k, v, cache_k, layer_idx, offset, k_valid_from)
        new_cv = cache_v
    elif flash_prefill:
        from ..ops.flash_attention import flash_attention  # lazy import
        new_ck, new_cv = write_kv_layer(cache_k, cache_v, k, v, layer_idx,
                                        offset)
        attn_out = flash_attention(
            q, k, v, interpret=jax.default_backend() != "tpu")
    else:
        attn_out, new_ck, new_cv = cached_attention_inplace(
            q, k, v, cache_k, cache_v, layer_idx, offset, k_valid_from)
    attn_out = linear(merge_heads(attn_out),
                      block_params["attn"]["c_proj"]["kernel"],
                      block_params["attn"]["c_proj"]["bias"])
    h = h + attn_out
    m = layer_norm(h, block_params["ln_2"]["scale"], block_params["ln_2"]["bias"], eps)
    if mlp_fn is None:
        m = linear(gelu_new(linear(m, block_params["mlp"]["c_fc"]["kernel"],
                                   block_params["mlp"]["c_fc"]["bias"])),
                   block_params["mlp"]["c_proj"]["kernel"],
                   block_params["mlp"]["c_proj"]["bias"])
    else:
        m = mlp_fn(block_params, m)
    return h + m, new_ck, new_cv


def apply_blocks(blocks: Params, h: jnp.ndarray, config: GPT2Config,
                 cache: Optional[KVCache] = None, remat: bool = False,
                 k_valid_from: Optional[jnp.ndarray] = None, mesh=None,
                 valid: Optional[jnp.ndarray] = None,
                 flash_prefill: bool = False,
                 decode_kernel: Optional[str] = None,
                 ) -> Tuple[jnp.ndarray, Optional[KVCache]]:
    """Run a stack of blocks (leading layer axis) via ``lax.scan``.

    ``blocks`` leaves are ``[L, ...]``; ``cache`` (if given) carries matching
    ``[L, B, H, max_seq, hd]`` buffers. One compiled body serves every layer —
    the TPU-shaped replacement for the reference's per-module Python loop
    (server.py:84-85, 99-100).

    ``remat=True`` checkpoints each block under reverse-mode AD: the
    backward pass recomputes block activations instead of storing all
    ``L`` of them — the standard HBM-for-FLOPs trade for training.

    ``valid`` ([L] bool, no-cache path only) masks padding layers to
    identity — the mechanism behind unequal pipeline stages, where stage
    blocks are zero-padded to a common count (``parallel.partition.
    stack_stage_params_padded``). A masked layer contributes nothing to
    the output, so its (zero) parameters also receive exactly zero
    gradient and stay zero under training.
    """
    eps = config.layer_norm_epsilon
    n_head = config.n_head

    if cache is None:
        if valid is None:
            def body(carry, layer_params):
                out, _, _ = _block(layer_params, carry, n_head, eps, None,
                                   None, 0, config.attention_impl,
                                   k_valid_from, mesh)
                return out, None
        else:
            blocks = (blocks, valid)

            def body(carry, xs):
                layer_params, valid_l = xs
                out, _, _ = _block(layer_params, carry, n_head, eps, None,
                                   None, 0, config.attention_impl,
                                   k_valid_from, mesh)
                return jnp.where(valid_l, out, carry), None

        if remat:
            body = jax.checkpoint(body)
        h, _ = jax.lax.scan(body, h, blocks)
        return h, None

    offset = cache.length
    n_blocks = jax.tree_util.tree_leaves(blocks)[0].shape[0]

    # Cache rides the CARRY (in-place column updates), not xs/ys — see
    # ops.attention.write_kv_layer for the memory-behavior rationale.
    # ``valid`` masks padding layers to identity (uneven pipeline stages,
    # parallel.partition.stack_stage_params_padded): a padded layer's
    # output is discarded and its cache slice — written with garbage
    # derived from zero params — is never read by any real layer.
    def body(carry, xs):
        h, K, V = carry
        if valid is None:
            layer_params, li = xs
        else:
            layer_params, li, valid_l = xs
        out, K, V = _block(layer_params, h, n_head, eps, K, V,
                           offset, k_valid_from=k_valid_from,
                           flash_prefill=flash_prefill, layer_idx=li,
                           decode_kernel=decode_kernel)
        if valid is not None:
            out = jnp.where(valid_l, out, h)
        return (out, K, V), None

    xs = ((blocks, jnp.arange(n_blocks)) if valid is None
          else (blocks, jnp.arange(n_blocks), valid))
    (h, new_k, new_v), _ = jax.lax.scan(body, (h, cache.k, cache.v), xs)
    new_len = cache.length + jnp.asarray(h.shape[1], dtype=jnp.int32)
    return h, KVCache(k=new_k, v=new_v, length=new_len)


def final_logits(params: Params, h: jnp.ndarray, eps: float) -> jnp.ndarray:
    """ln_f followed by the tied LM head (logits = h @ wte.T).

    Equivalent of the reference's ShardB tail (ln_f -> lm_head,
    server.py:101-102); tying to ``wte`` matches GPT-2's actual weight
    sharing, which HF also applies. Logits accumulate in float32 even under
    bfloat16 weights/activations so argmax/sampling see full-precision
    scores (bf16 logits would quantize ~3 decimal digits and break greedy
    tie behavior).
    """
    h = layer_norm(h, params["ln_f"]["scale"], params["ln_f"]["bias"], eps)
    from ..ops.quant import is_quantized
    if is_quantized(params["wte"]):  # int8 table: fold scale into h
        from ..ops.quant import head_logits
        return head_logits(h, params["wte"])
    return jnp.einsum("bsd,vd->bsv", h, params["wte"],
                      preferred_element_type=jnp.float32)


def forward(params: Params, input_ids: jnp.ndarray,
            config: GPT2Config, remat: bool = False, mesh=None) -> jnp.ndarray:
    """Full no-cache forward: [B, S] -> [B, S, vocab] logits.

    The parity oracle against HF GPT-2 (SURVEY.md §4 item 1) and the compat
    ``/forward`` + ``/forward_b`` composition both go through here.
    ``remat`` is for the training path (see ``apply_blocks``); ``mesh`` is
    required when ``config.attention_impl == "ring"`` (the sequence-
    parallel long-context path shards attention over the mesh's sp axis).
    """
    h = embed(params, input_ids, 0)
    h, _ = apply_blocks(params["blocks"], h, config, remat=remat, mesh=mesh)
    return final_logits(params, h, config.layer_norm_epsilon)


def mega_step(blocks: Params, h: jnp.ndarray, config: GPT2Config, cache,
              pad, decode_kernel: str):
    """One whole-stack megakernel decode step over an embedded
    ``[B, 1, D]`` hidden state — all the stacked blocks in one launch
    (ops.decode_layer, the dispatch-overhead fix). THE single gpt2-family
    mega route, shared by ``forward_with_cache`` and the stage runner
    (parallel.partition). Returns ``(h, cache)``, or ``None`` when the
    batch exceeds the kernel's VMEM budget — the caller downgrades to
    the per-layer kernel (``ops.decode_layer.mega_downgrade``)."""
    from ..ops.decode_layer import MAX_BATCH, decode_layers
    if h.shape[0] > MAX_BATCH:
        return None
    h, KV = decode_layers(blocks, h, cache.k, cache.length,
                          k_valid_from=pad, n_head=config.n_head,
                          eps=config.layer_norm_epsilon,
                          interpret=decode_kernel == "mega-interpret")
    return h, KVCache(k=KV, v=cache.v, length=cache.length + 1)


def forward_with_cache(params: Params, input_ids: jnp.ndarray,
                       config: GPT2Config, cache: KVCache,
                       pad: Optional[jnp.ndarray] = None,
                       flash_prefill: bool = False,
                       decode_kernel: Optional[str] = None,
                       ) -> Tuple[jnp.ndarray, KVCache]:
    """Cached forward (prefill when cache.length==0, decode step otherwise).

    Returns full-sequence logits and the updated cache. The decode engine
    (runtime.engine) jits this once for prefill shapes and once for the
    single-token step.

    ``pad`` ([B] int32, optional) enables ragged batches of left-padded
    prompts: row b's first ``pad[b]`` cache slots are pad tokens, so its
    positions shift down by ``pad[b]`` and those slots are masked as keys.
    Cache indices stay uniform across rows (the point of left-padding: one
    ``dynamic_update_slice`` serves the whole batch).
    """
    from ..ops.decode_layer import mega_downgrade, mega_requested
    if mega_requested(decode_kernel, input_ids.shape[1]):
        offset = (cache.length if pad is None
                  else cache.length - pad[:, None])
        h = embed(params, input_ids, offset)
        step = mega_step(params["blocks"], h, config, cache, pad,
                         decode_kernel)
        if step is not None:
            h, cache = step
            return final_logits(params, h,
                                config.layer_norm_epsilon), cache
        decode_kernel = mega_downgrade(decode_kernel)
    if pad is None:
        h = embed(params, input_ids, cache.length)
        h, cache = apply_blocks(params["blocks"], h, config, cache,
                                flash_prefill=flash_prefill,
                                decode_kernel=decode_kernel)
    else:
        h = embed(params, input_ids, cache.length - pad[:, None])
        h, cache = apply_blocks(params["blocks"], h, config, cache,
                                k_valid_from=pad,
                                decode_kernel=decode_kernel)
    return final_logits(params, h, config.layer_norm_epsilon), cache


def make_cache(config: GPT2Config, batch: int, max_seq: int,
               dtype=jnp.float32) -> KVCache:
    """Allocate a fixed-size KV cache.

    ``max_seq`` is bounded by ``n_positions``: past the learned position
    table, ``wpe`` gathers and cache writes would silently clamp (XLA
    out-of-bounds semantics) and corrupt generation instead of erroring.
    """
    if max_seq > config.n_positions:
        raise ValueError(
            f"max_seq={max_seq} exceeds n_positions={config.n_positions}; "
            "decode past the position table would silently clamp")
    return KVCache.create(config.n_layer, batch, config.n_head, max_seq,
                          config.head_dim, dtype)
