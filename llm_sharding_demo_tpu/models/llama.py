"""LLaMA-family model (RMSNorm + RoPE + SwiGLU + GQA) as pure JAX.

Second dense model family, beyond reference parity (the reference serves
GPT-2 only, reference server.py:41). Same pure-pytree design and public
surface as ``models.gpt2`` — ``init_params`` / ``forward`` /
``forward_with_cache`` / ``make_cache`` over stacked ``[n_layer, ...]``
block leaves scanned by ``lax.scan`` — so the decode engine, speculative
decoding, serving, quantization, and checkpointing all work via the
family registry (``models.family_module``) without knowing the
architecture. Differences from GPT-2 that matter here:

- **RoPE instead of a learned position table** (``ops.rope``): positions
  are computed, not gathered, so context length is bounded only by cache
  memory — this family is the framework's genuine long-context path
  (GPT-2 hard-stops at 1024 learned positions, the reference's ceiling).
- **Grouped-query attention**: ``n_kv_head <= n_head``; the KV cache is
  allocated at kv-head width (``ops.attention`` handles grouped q/kv
  natively), shrinking decode's cache traffic by ``n_head/n_kv_head``.
- **RMSNorm** (no biases anywhere) and **SwiGLU** MLP
  (``down(silu(gate(x)) * up(x))``).
- **Untied LM head** (HF ``LlamaForCausalLM`` default).

Numerics mirror HF ``modeling_llama`` (fp32 norm statistics, fp32 rotary
angles, fp32 logits) so the logit-parity oracle
(tests/test_llama.py) pins conversion + forward exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import (KVCache, cached_attention_inplace,
                             causal_attention, merge_heads, split_heads,
                             write_kv_layer)
from ..ops.layers import linear, rms_norm
from ..ops.rope import apply_rope, rope_angles

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Architecture hyperparameters (mirrors the HF ``LlamaConfig`` fields
    we use; ``n_*`` naming kept consistent with ``GPT2Config``)."""

    vocab_size: int = 32000
    n_positions: int = 4096          # cache/serving bound, NOT a table size
    n_embd: int = 768                # hidden_size
    n_layer: int = 12
    n_head: int = 12
    n_kv_head: int = 12              # < n_head => grouped-query attention
    intermediate_size: int = 2048
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    # "xla" | "pallas" | "ring" — same contract as GPT2Config. pallas/ring
    # run on full-width K/V (GQA heads repeated first); the no-repeat
    # grouped path is the default xla einsum.
    attention_impl: str = "xla"

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    def __post_init__(self):
        if self.n_embd % self.n_head != 0:
            raise ValueError(
                f"n_embd={self.n_embd} not divisible by n_head={self.n_head}")
        if self.n_head % self.n_kv_head != 0:
            raise ValueError(f"n_head={self.n_head} not a multiple of "
                             f"n_kv_head={self.n_kv_head}")
        if self.attention_impl not in ("xla", "pallas", "ring"):
            raise ValueError(
                f"attention_impl={self.attention_impl!r} not xla|pallas|ring")


# Static-analysis/planner contract (tools/graftcheck/costmodel): the
# family's sharding facts — see ``models.gpt2.SHARDING_DESCRIPTOR`` for
# the schema. The GQA head-ratio lives in ``tp_divisors``: a tensor axis
# must divide BOTH head counts (attention shards whole q heads AND whole
# kv heads; a tp that splits a kv group would replicate cache writes),
# which is exactly the engine's own TP_DECODE guard. The derived
# PartitionSpec tree is pinned equal to ``spmd.llama_param_pspecs`` by
# tests/test_graftplan.py.
SHARDING_DESCRIPTOR = {
    "column": ("blocks.attn.wq", "blocks.attn.wk", "blocks.attn.wv",
               "blocks.mlp.gate", "blocks.mlp.up"),
    "row": ("blocks.attn.wo", "blocks.mlp.down"),
    "expert": (),
    "tp_divisors": ("n_head", "n_kv_head"),
    # kvp (KV-partition, Helix-style) shards the PAGED POOL's kv-head
    # dim only — query heads replicate, so unlike tp the GQA ratio does
    # not constrain it; only the kv head count must divide
    "kvp_divisors": ("n_kv_head",),
    "ep_divisors": (),
}


# "llama-124m" is the GPT-2-124M-comparable geometry used by the bench;
# "llama-tiny" a test/smoke size. Both use GQA (n_kv_head < n_head) so the
# family's distinguishing feature is always exercised.
CONFIGS: Dict[str, LlamaConfig] = {
    "llama-tiny": LlamaConfig(vocab_size=256, n_positions=512, n_embd=32,
                              n_layer=2, n_head=4, n_kv_head=2,
                              intermediate_size=64),
    "llama-124m": LlamaConfig(vocab_size=32000, n_positions=4096, n_embd=768,
                              n_layer=12, n_head=12, n_kv_head=4,
                              intermediate_size=2048),
}


def init_params(config: LlamaConfig, key: jax.Array,
                dtype=jnp.float32) -> Params:
    """Random-init parameters; stacked ``[n_layer, ...]`` block leaves.

    All matmul weights live under ``.../kernel`` in the ``[in, out]``
    layout so ``ops.quant.quantize_params`` and the serving int8 path
    apply unchanged.
    """
    d, l = config.n_embd, config.n_layer
    hd, i = config.head_dim, config.intermediate_size
    kv = config.n_kv_head * hd
    std = 0.02
    keys = jax.random.split(key, 9)

    def normal(k, shape):
        return (jax.random.normal(k, shape) * std).astype(dtype)

    return {
        "wte": normal(keys[0], (config.vocab_size, d)),
        "blocks": {
            "ln_attn": {"scale": jnp.ones((l, d), dtype)},
            "attn": {
                "wq": {"kernel": normal(keys[1], (l, d, d))},
                "wk": {"kernel": normal(keys[2], (l, d, kv))},
                "wv": {"kernel": normal(keys[3], (l, d, kv))},
                "wo": {"kernel": normal(keys[4], (l, d, d))},
            },
            "ln_mlp": {"scale": jnp.ones((l, d), dtype)},
            "mlp": {
                "gate": {"kernel": normal(keys[5], (l, d, i))},
                "up": {"kernel": normal(keys[6], (l, d, i))},
                "down": {"kernel": normal(keys[7], (l, i, d))},
            },
        },
        "ln_f": {"scale": jnp.ones((d,), dtype)},
        "lm_head": {"kernel": normal(keys[8], (d, config.vocab_size))},
    }


def _block(block_params: Params, h: jnp.ndarray, config: LlamaConfig,
           cos: jnp.ndarray, sin: jnp.ndarray,
           cache_k: Optional[jnp.ndarray], cache_v: Optional[jnp.ndarray],
           offset, k_valid_from: Optional[jnp.ndarray] = None,
           mesh=None, flash_prefill: bool = False, layer_idx=None,
           decode_kernel: Optional[str] = None,
           ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray],
                      Optional[jnp.ndarray]]:
    """One pre-norm llama block; optionally reads/writes the KV cache.

    ``cache_k``/``cache_v`` are the FULL stacked ``[L, B, Hkv, max_seq,
    hd]`` buffers with ``layer_idx`` selecting this block's slice — the
    in-place carry pattern (see ``ops.attention.write_kv_layer``)."""
    a = rms_norm(h, block_params["ln_attn"]["scale"], config.rms_norm_eps)
    attn = block_params["attn"]
    q = split_heads(linear(a, attn["wq"]["kernel"]), config.n_head)
    k = split_heads(linear(a, attn["wk"]["kernel"]), config.n_kv_head)
    v = split_heads(linear(a, attn["wv"]["kernel"]), config.n_kv_head)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if cache_k is None:
        impl = config.attention_impl

        def repeat_kv(k, v):
            # the pallas/ring kernels want equal q/kv head counts; repeat
            # (HF repeat_kv ordering) — a training-path materialization,
            # the cached decode path below never repeats, and neither do
            # the XLA fallbacks (grouped einsum handles GQA natively)
            g = config.n_head // config.n_kv_head
            return ((jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1))
                    if g > 1 else (k, v))

        if impl == "pallas":
            from ..ops.flash_attention import (flash_attention,
                                               flash_profitable)
            if flash_profitable(q.shape[2]):
                kf, vf = repeat_kv(k, v)
                attn_out = flash_attention(
                    q, kf, vf, interpret=jax.default_backend() != "tpu")
            else:
                # below the measured crossover the XLA einsum wins
                attn_out = causal_attention(q, k, v, q_offset=offset,
                                            k_valid_from=k_valid_from)
        elif impl == "ring":
            from ..ops.ring_attention import ring_attention
            if mesh is None:
                raise ValueError("attention_impl='ring' needs a mesh with "
                                 "an 'sp' axis: pass forward(..., mesh=mesh)")
            if k_valid_from is not None:
                raise NotImplementedError(
                    "ring attention does not support ragged batches")
            kf, vf = repeat_kv(k, v)
            attn_out = ring_attention(q, kf, vf, mesh, axis="sp")
        else:
            attn_out = causal_attention(q, k, v, q_offset=offset,
                                        k_valid_from=k_valid_from)
        new_ck = new_cv = None
    elif decode_kernel is not None:
        # FUSED cache mode (ops.attention.create_fused_cache): cache_k is
        # the fused [L, B, Hkv, Smax, 2*hd] buffer, cache_v a placeholder
        from ..ops.attention import (cached_attention_fused,
                                     write_kv_layer_fused)
        if flash_prefill:
            from ..ops.flash_attention import flash_attention
            new_ck = write_kv_layer_fused(cache_k, k, v, layer_idx, offset)
            g = config.n_head // config.n_kv_head
            kf = jnp.repeat(k, g, axis=1) if g > 1 else k
            vf = jnp.repeat(v, g, axis=1) if g > 1 else v
            attn_out = flash_attention(
                q, kf, vf, interpret=jax.default_backend() != "tpu")
        elif q.shape[2] == 1:
            # GQA-native flash-decode kernel: g = n_head/n_kv_head query
            # heads ride each kv head's block stream, K/V never repeat
            from ..ops.decode_attention import decode_attention
            attn_out, new_ck = decode_attention(
                q, k, v, cache_k, layer_idx, offset, k_valid_from,
                interpret=decode_kernel == "interpret")
        else:
            attn_out, new_ck = cached_attention_fused(
                q, k, v, cache_k, layer_idx, offset, k_valid_from)
        new_cv = cache_v
    elif flash_prefill:
        # fresh-cache prefill (offset 0, no pad): cached attention is
        # plain causal attention over the new K/V — write the cache at
        # kv-head width, run the flash kernel on repeated heads (the
        # kernel wants equal q/kv head counts; a one-off prefill
        # materialization, decode still reads the narrow cache)
        from ..ops.flash_attention import flash_attention
        new_ck, new_cv = write_kv_layer(cache_k, cache_v, k, v, layer_idx,
                                        offset)
        g = config.n_head // config.n_kv_head
        kf = jnp.repeat(k, g, axis=1) if g > 1 else k
        vf = jnp.repeat(v, g, axis=1) if g > 1 else v
        attn_out = flash_attention(
            q, kf, vf, interpret=jax.default_backend() != "tpu")
    else:
        attn_out, new_ck, new_cv = cached_attention_inplace(
            q, k, v, cache_k, cache_v, layer_idx, offset, k_valid_from)
    h = h + linear(merge_heads(attn_out), attn["wo"]["kernel"])
    m = rms_norm(h, block_params["ln_mlp"]["scale"], config.rms_norm_eps)
    mlp = block_params["mlp"]
    m = linear(jax.nn.silu(linear(m, mlp["gate"]["kernel"]))
               * linear(m, mlp["up"]["kernel"]), mlp["down"]["kernel"])
    return h + m, new_ck, new_cv


def _embed(params: Params, input_ids: jnp.ndarray) -> jnp.ndarray:
    wte = params["wte"]
    from ..ops.quant import is_quantized
    if is_quantized(wte):
        from ..ops.quant import embed_rows
        return embed_rows(wte, input_ids)
    return wte[input_ids]


def _angles(config: LlamaConfig, seq_len: int, offset,
            pad: Optional[jnp.ndarray]):
    """(cos, sin) for positions ``offset + arange(S)`` (per-row shifted
    down by ``pad`` for left-padded ragged batches; pad columns clip to
    position 0 — masked as keys, never read as outputs)."""
    pos = offset + jnp.arange(seq_len)
    if pad is not None:
        pos = jnp.maximum(pos[None, :] - pad[:, None], 0)   # [B, S]
    return rope_angles(pos, config.head_dim, config.rope_theta)


def _final(params: Params, h: jnp.ndarray, config: LlamaConfig) -> jnp.ndarray:
    h = rms_norm(h, params["ln_f"]["scale"], config.rms_norm_eps)
    from ..ops.quant import is_quantized
    kernel = params["lm_head"]["kernel"]
    if is_quantized(kernel):
        return linear(h, kernel).astype(jnp.float32)
    return jnp.einsum("bsd,dv->bsv", h, kernel,
                      preferred_element_type=jnp.float32)


def apply_blocks(blocks: Params, h: jnp.ndarray, config: LlamaConfig,
                 cos: jnp.ndarray, sin: jnp.ndarray,
                 cache: Optional[KVCache] = None, remat: bool = False,
                 k_valid_from: Optional[jnp.ndarray] = None, mesh=None,
                 flash_prefill: bool = False,
                 valid: Optional[jnp.ndarray] = None,
                 decode_kernel: Optional[str] = None,
                 ) -> Tuple[jnp.ndarray, Optional[KVCache]]:
    """Run a stack of llama blocks (leading layer axis) via ``lax.scan`` —
    the llama sibling of ``gpt2.apply_blocks``, factored out so the
    pipeline partitioner (parallel.partition) and the GPipe schedule
    (parallel.gpipe) can run a STAGE's block slice.

    ``valid`` ([L] bool, no-cache path only) masks padding layers to
    identity — the uneven-pipeline-stage mechanism, exactly as in
    ``gpt2.apply_blocks``."""
    if cache is None:
        if valid is None:
            def body(carry, layer_params):
                out, _, _ = _block(layer_params, carry, config, cos, sin,
                                   None, None, 0, k_valid_from=k_valid_from,
                                   mesh=mesh)
                return out, None
        else:
            blocks = (blocks, valid)

            def body(carry, xs):
                layer_params, valid_l = xs
                out, _, _ = _block(layer_params, carry, config, cos, sin,
                                   None, None, 0, k_valid_from=k_valid_from,
                                   mesh=mesh)
                return jnp.where(valid_l, out, carry), None

        if remat:
            body = jax.checkpoint(body)
        h, _ = jax.lax.scan(body, h, blocks)
        return h, None

    offset = cache.length
    n_blocks = jax.tree_util.tree_leaves(blocks)[0].shape[0]

    # Cache rides the CARRY (in-place column updates), not xs/ys — see
    # ops.attention.write_kv_layer for the memory-behavior rationale.
    # ``valid`` masks padding layers to identity, as in gpt2.apply_blocks
    # (their cache slices take garbage writes no real layer ever reads).
    def body(carry, xs):
        h, K, V = carry
        if valid is None:
            layer_params, li = xs
        else:
            layer_params, li, valid_l = xs
        out, K, V = _block(layer_params, h, config, cos, sin, K, V, offset,
                           k_valid_from=k_valid_from,
                           flash_prefill=flash_prefill, layer_idx=li,
                           decode_kernel=decode_kernel)
        if valid is not None:
            out = jnp.where(valid_l, out, h)
        return (out, K, V), None

    xs = ((blocks, jnp.arange(n_blocks)) if valid is None
          else (blocks, jnp.arange(n_blocks), valid))
    (h, new_k, new_v), _ = jax.lax.scan(body, (h, cache.k, cache.v), xs)
    new_len = cache.length + jnp.asarray(h.shape[1], dtype=jnp.int32)
    return h, KVCache(new_k, new_v, new_len)


def forward(params: Params, input_ids: jnp.ndarray, config: LlamaConfig,
            remat: bool = False, mesh=None) -> jnp.ndarray:
    """Full no-cache forward: [B, S] -> [B, S, vocab] float32 logits."""
    h = _embed(params, input_ids)
    cos, sin = _angles(config, input_ids.shape[1], 0, None)
    h, _ = apply_blocks(params["blocks"], h, config, cos, sin,
                        remat=remat, mesh=mesh)
    return _final(params, h, config)


def mega_step(blocks: Params, h: jnp.ndarray, config: LlamaConfig, cache,
              pad, cos, sin, decode_kernel: str):
    """One whole-stack megakernel decode step — the llama-family twin of
    ``gpt2.mega_step`` (shared by ``forward_with_cache`` and the stage
    runner). ``cos``/``sin`` are the step's rotary angles in any of
    ``_angles``' single-position layouts; they normalize to the
    ``[B, hd]`` the kernel wants. Returns ``(h, cache)`` or ``None``
    past the kernel's batch budget."""
    from ..ops.decode_layer import MAX_BATCH, decode_layers_llama
    b = h.shape[0]
    if b > MAX_BATCH:
        return None
    cos1 = jnp.broadcast_to(cos.reshape(-1, config.head_dim),
                            (b, config.head_dim))
    sin1 = jnp.broadcast_to(sin.reshape(-1, config.head_dim),
                            (b, config.head_dim))
    h, KV = decode_layers_llama(blocks, h, cache.k, cache.length, cos1,
                                sin1, k_valid_from=pad,
                                n_head=config.n_head,
                                eps=config.rms_norm_eps,
                                interpret=decode_kernel == "mega-interpret")
    return h, KVCache(KV, cache.v, cache.length + 1)


def forward_with_cache(params: Params, input_ids: jnp.ndarray,
                       config: LlamaConfig, cache: KVCache,
                       pad: Optional[jnp.ndarray] = None,
                       flash_prefill: bool = False,
                       decode_kernel: Optional[str] = None,
                       ) -> Tuple[jnp.ndarray, KVCache]:
    """Cached forward (prefill when cache.length==0, decode otherwise).

    Same contract as ``gpt2.forward_with_cache`` — multi-token steps at a
    dynamic offset work, which is what speculative decoding's verify
    forward relies on.
    """
    h = _embed(params, input_ids)
    offset = cache.length
    cos, sin = _angles(config, input_ids.shape[1], offset, pad)
    from ..ops.decode_layer import mega_downgrade, mega_requested
    if mega_requested(decode_kernel, input_ids.shape[1]):
        step = mega_step(params["blocks"], h, config, cache, pad, cos, sin,
                         decode_kernel)
        if step is not None:
            h, cache = step
            return _final(params, h, config), cache
        decode_kernel = mega_downgrade(decode_kernel)
    # structural guard (mirrors gpt2): the flash branch has no pad mask,
    # so ragged batches always take the masked cached-attention path
    flash_prefill = flash_prefill and pad is None
    h, cache = apply_blocks(params["blocks"], h, config, cos, sin, cache,
                            k_valid_from=pad, flash_prefill=flash_prefill,
                            decode_kernel=decode_kernel)
    return _final(params, h, config), cache


def make_cache(config: LlamaConfig, batch: int, max_seq: int,
               dtype=jnp.float32) -> KVCache:
    """KV cache at kv-head width ([L, B, n_kv_head, max_seq, hd]).

    ``n_positions`` bounds ``max_seq`` as a config contract (cache sizing /
    serving limit), not a table size — raise it in the config and longer
    contexts work with the same weights (RoPE).
    """
    if max_seq > config.n_positions:
        raise ValueError(
            f"max_seq={max_seq} exceeds n_positions={config.n_positions} "
            "(the configured serving/cache bound)")
    return KVCache.create(config.n_layer, batch, config.n_kv_head, max_seq,
                          config.head_dim, dtype)
