"""graftload driver: open-loop load against the real in-process app.

``run_load`` fires a seeded :mod:`loadgen.schedule` at the serving
surface (``serving/http.py`` TestClient — the exact production
dispatch path, no sockets) and reduces the outcomes to the two rows
bench.py journals:

- the **Pareto point** (offered rate vs achieved throughput vs tail
  latency) — one per ``(profile, rate_scale)``;
- the **SLO attainment** row — per declared ``SLO_POLICY`` metric, the
  observed percentile against its target, plus **goodput under SLO**:
  requests that completed INSIDE their declared e2e/ttft/tpot budgets,
  with typed sheds (429 admission, 503 breaker/park/engine) counted
  separately — a shed is honest refusal, a miss is a broken promise,
  and conflating them is how overload hides in dashboards.

Open vs closed loop: ``mode="open"`` (the default) fires arrival k at
its scheduled offset on its own thread regardless of what earlier
requests are doing — queue growth under overload lands in the measured
tail, exactly like production. ``mode="closed"`` (comparison/baseline
only) runs ``width`` workers back-to-back; at saturation it throttles
itself and under-reports p99 (pinned by tests/test_graftload.py).
``mode="serial"`` is closed at width 1 — the deterministic replay
configuration (same seed -> byte-identical per-request outputs).

Per-request TTFT/TPOT come from the flight recorder (the driver joins
traces by X-Request-ID), and mid-run occupancy (queue depth, batch
occupancy, pool blocks, breaker state) rides the existing graftscope
series — ``occupancy_summary`` reduces the same rings /debug/profile
serves.
"""

from __future__ import annotations

import dataclasses
import queue as _queuemod
import threading
import time
from typing import Dict, List, Optional

from ..utils import graftscope, grafttime
from .profiles import SLO_POLICY, WorkloadProfile
from .schedule import Arrival, schedule

# graftscope series the occupancy summary reduces (queue/batch/pool/
# breaker/plan — the load-level view of the serving stack's internal
# state; auto_plan_active puts graftwatch plan switches on the same
# timeline as the queue depth that provoked them)
OCCUPANCY_SERIES = ("queue_depth", "batch_occupancy",
                    "kv_cache_blocks_in_use", "iter_live_rows",
                    "hop_breaker_open", "auto_plan_active")

# Timeline contract (tools/graftcheck timeline pass): every fired
# arrival lands on the unified causal stream (utils/grafttime) — the
# open-loop schedule is the demand side of every queue/occupancy/shed
# trajectory, and without it on the same clock "the pool filled up"
# has no visible cause.
TIMELINE_EVENTS = {
    "arrival": "_post",
}

# Fault contract (tools/graftcheck faults pass): the driver's one
# blocking boundary is the in-process client hop it measures through.
# The wait is bounded by run_load's join WATCHDOG (TimeoutError once
# ``join_timeout_s`` passes the schedule horizon), and a dead app is a
# measured outcome (status=-1 row), never a hang or a swallowed fault.
FAULT_POLICY = {
    "client.post": ("watchdog", "none",
                    "run_load join watchdog; failures land as "
                    "status=-1 outcomes in the report"),
}


@dataclasses.dataclass
class Outcome:
    """One request's observed result (client side + trace join)."""

    k: int
    request_id: str
    status: int = 0
    code: str = ""              # typed error code ("" on success)
    latency_s: float = 0.0
    abandoned: bool = False     # scheduled walk-away (short deadline)
    generated: Optional[str] = None
    ttft_s: Optional[float] = None    # joined from the flight recorder
    tpot_s: Optional[float] = None
    new_tokens: int = 0


def _post(client, profile: WorkloadProfile, a: Arrival,
          rid: str) -> Outcome:
    body = {"prompt": a.prompt, "max_new_tokens": a.max_new,
            "mode": a.mode}
    if a.mode == "sample":
        body["seed"] = a.seed
    headers = {"X-Request-ID": rid,
               "X-Workload-Profile": profile.name}
    if a.deadline_ms is not None:
        headers["X-Deadline-Ms"] = str(a.deadline_ms)
    t0 = time.perf_counter()
    out = Outcome(k=a.k, request_id=rid, abandoned=a.abandoned)
    grafttime.emit("arrival", rid=rid, k=a.k, profile=profile.name,
                   sched_t=round(a.t, 6), t=t0)
    try:
        r = client.post("/generate", json=body, headers=headers)
        out.status = r.status_code
        payload = r.json()
        if r.status_code == 200 and "generated" in payload:
            out.generated = payload["generated"]
        else:
            out.code = str(payload.get("error",
                                       payload.get("detail", "")))[:80]
            if out.status == 200:
                # reference-parity 200-with-error bodies (bad request
                # shapes) are driver errors, not serving outcomes
                out.status = 400
    except Exception as e:  # noqa: BLE001 — a dead client IS a result
        out.status = -1
        out.code = f"{type(e).__name__}: {e}"[:80]
    out.latency_s = time.perf_counter() - t0
    return out


def _join_traces(outcomes: List[Outcome], recorder) -> None:
    """Attach ttft/tpot/new_tokens from the flight recorder's traces
    (matched by X-Request-ID; requests that fell off the bounded ring
    simply keep client-side numbers only)."""
    if recorder is None:
        return
    by_id: Dict[str, dict] = {}
    # snapshot is newest-first; walk it oldest-first so a request id
    # reused across sequential runs on a shared recorder (e.g. the
    # bench Pareto sweep) joins the NEWEST trace
    for t in reversed(recorder.snapshot(n=None)):
        by_id[t["request_id"]] = t
    for o in outcomes:
        t = by_id.get(o.request_id)
        if t is None:
            continue
        labels = t.get("labels", {})
        ttft_ms = labels.get("ttft_ms")
        if ttft_ms is not None:
            o.ttft_s = float(ttft_ms) / 1e3
        o.new_tokens = int(labels.get("new_tokens", 0) or 0)
        if o.ttft_s is not None and o.new_tokens > 1:
            decode_s = max(t["duration_ms"] / 1e3 - o.ttft_s, 0.0)
            o.tpot_s = decode_s / (o.new_tokens - 1)


def run_load(client, profile: WorkloadProfile, seed: int, n: int,
             rate_scale: float = 1.0, mode: str = "open",
             width: int = 4, recorder=None,
             join_timeout_s: float = 300.0, trend=None) -> dict:
    """Drive ``n`` scheduled arrivals of ``(seed, profile)`` at the
    app behind ``client`` and return the reduced load report (see
    module docstring). ``recorder`` is the app's FlightRecorder (pass
    the instance handed to ``create_app`` so the TTFT/TPOT join sees
    every request; size it >= n). ``trend`` is an optional
    ``grafttrend.TrendReducer``: the driver polls it once after the
    run drains and evaluates the declared watches, so a load run
    doubles as ONE trend observation window — the report gains a
    ``trend`` block naming the watches THIS run tripped (the bench
    ``trend_detection`` row's quiet-vs-burst split rides on it)."""
    if mode not in ("open", "closed", "serial"):
        raise ValueError(f"unknown load mode {mode!r}")
    arrivals = schedule(profile, seed, n, rate_scale)
    outcomes: List[Optional[Outcome]] = [None] * n
    rid_of = [f"{profile.name}-{seed}-{a.k:05d}" for a in arrivals]
    horizon_s = arrivals[-1].t if arrivals else 0.0

    occ_since = graftscope.now_ms()   # window THIS run's occupancy
    t0 = time.perf_counter()
    if mode == "open":
        def fire(a: Arrival):
            delay = a.t - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            outcomes[a.k] = _post(client, profile, a, rid_of[a.k])

        threads = [threading.Thread(target=fire, args=(a,), daemon=True)
                   for a in arrivals]
        for t in threads:
            t.start()
        # the join budget starts counting AFTER the schedule horizon —
        # a long low-rate run still has threads sleeping toward their
        # offsets, which is health, not a hang
        deadline = time.monotonic() + horizon_s + join_timeout_s
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))
        hung = sum(1 for t in threads if t.is_alive())
        if hung:
            raise TimeoutError(
                f"graftload: {hung}/{n} open-loop requests still in "
                f"flight {join_timeout_s}s past the schedule horizon")
    else:
        # closed loop: workers pull the same request bodies in order,
        # next only after the previous returns (arrival times ignored
        # — that self-throttling is the point of the comparison)
        q: "_queuemod.Queue[Arrival]" = _queuemod.Queue()
        for a in arrivals:
            q.put(a)
        n_workers = 1 if mode == "serial" else max(int(width), 1)

        def drain():
            while True:
                try:
                    a = q.get_nowait()
                except _queuemod.Empty:
                    return
                outcomes[a.k] = _post(client, profile, a, rid_of[a.k])

        if n_workers == 1:
            drain()
        else:
            threads = [threading.Thread(target=drain, daemon=True)
                       for _ in range(n_workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=join_timeout_s)
            if any(t.is_alive() for t in threads):
                raise TimeoutError("graftload: closed-loop workers hung")
    wall = time.perf_counter() - t0

    done: List[Outcome] = [o for o in outcomes if o is not None]
    _join_traces(done, recorder)
    report = summarize(profile, done, wall, seed=seed,
                       rate_scale=rate_scale, mode=mode,
                       width=(1 if mode == "serial" else width),
                       horizon_s=(horizon_s if mode == "open" else None))
    report["occupancy"] = occupancy_summary(since_ms=occ_since)
    if trend is not None:
        trend.poll()
        trips = trend.evaluate()
        report["trend"] = {"alerts_fired": len(trips),
                           "tripped": [a["watch"] for a in trips]}
    return report


# -- reduction ----------------------------------------------------------------


def _pct(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile without numpy (values unsorted ok)."""
    if not values:
        return None
    vs = sorted(values)
    idx = max(int(-(-q / 100.0 * len(vs) // 1)) - 1, 0)
    return vs[min(idx, len(vs) - 1)]


def _metric_values(metric: str, completed: List[Outcome],
                   ) -> List[float]:
    if metric == "e2e":
        return [o.latency_s for o in completed]
    if metric == "ttft":
        return [o.ttft_s for o in completed if o.ttft_s is not None]
    if metric == "tpot":
        return [o.tpot_s for o in completed if o.tpot_s is not None]
    raise KeyError(metric)


def summarize(profile: WorkloadProfile, outcomes: List[Outcome],
              wall_s: float, seed: int = 0, rate_scale: float = 1.0,
              mode: str = "open", width: int = 0,
              horizon_s: Optional[float] = None) -> dict:
    """Outcomes -> the journaled load report: Pareto fields, typed
    shed/miss split, declared-SLO attainment, goodput. ``horizon_s``
    is the SCHEDULE's span (last arrival offset) — the open-loop
    offered rate derives from it, not from completion wall time:
    deriving the Pareto x-axis from how long the system took to drain
    would reintroduce exactly the system-speed coupling open-loop
    generation exists to remove. Closed/serial modes (self-paced by
    construction) pass None and fall back to wall time."""
    policy = SLO_POLICY.get(profile.name, {})
    completed = [o for o in outcomes if o.status == 200]
    shed_429 = [o for o in outcomes if o.status == 429]
    s503 = [o for o in outcomes if o.status == 503]
    # 503s split three ways: deadline_exceeded on accepted work is an
    # SLO MISS; the abandonment profile's scheduled walk-aways are
    # demand that left (neither shed nor miss); everything else
    # (breaker open, park budget, engine fault) is a typed SHED —
    # honest refusal/degradation, never conflated with broken promises.
    # Note the walk-away netting is CLIENT-side knowledge: the
    # server's deadline_misses_total counts every budget death
    # (it cannot see intent), so it reads >= this row's miss count
    # under abandonment traffic — documented at the METRIC_CATALOG
    # entry.
    walked = [o for o in s503 if o.abandoned
              and o.code == "deadline_exceeded"]
    misses = [o for o in s503 if not o.abandoned
              and o.code == "deadline_exceeded"]
    shed_503 = [o for o in s503 if o.code != "deadline_exceeded"]
    errors = [o for o in outcomes
              if o.status not in (200, 429, 503)]
    demanded = max(len(outcomes) - len(walked), 0)

    toks = sum(o.new_tokens for o in completed)
    lat_ms = [o.latency_s * 1e3 for o in completed]

    # declared-SLO attainment, metric by metric
    slo_rows: Dict[str, dict] = {}
    attained_n = 0
    for metric, (target, pct) in sorted(policy.items()):
        if metric == "deadline_miss":
            observed = (len(misses) / demanded) if demanded else 0.0
            ok = observed <= target
            row = {"target": target, "percentile": pct,
                   "observed_miss_fraction": round(observed, 4),
                   "attained": ok}
        else:
            values = _metric_values(metric, completed)
            p = _pct(values, pct)
            ok = p is not None and p <= target
            row = {"target_s": target, "percentile": pct,
                   "observed_s": None if p is None else round(p, 4),
                   "samples": len(values), "attained": ok}
        slo_rows[metric] = row
        attained_n += bool(ok)

    # goodput: completions whose EVERY declared latency budget
    # PROVABLY held — a declared metric with no measured value (the
    # flight-recorder join missed: no recorder, or the rid fell off
    # the bounded ring) counts AGAINST goodput, never silently for it;
    # an unprovable promise must not inflate the gated number
    def in_slo(o: Outcome) -> bool:
        for metric, (target, _pct_) in policy.items():
            if metric == "deadline_miss":
                continue
            if metric == "tpot" and o.new_tokens <= 1:
                continue       # no inter-token interval exists to bind
            v = {"e2e": o.latency_s, "ttft": o.ttft_s,
                 "tpot": o.tpot_s}[metric]
            if v is None or v > target:
                return False
        return True

    good = [o for o in completed if in_slo(o)]
    return {
        "profile": profile.name,
        "seed": seed,
        "mode": mode,
        "width": width,
        "rate_scale": rate_scale,
        "offered": len(outcomes),
        "offered_rps": round(
            len(outcomes) / (horizon_s if horizon_s else wall_s), 3)
        if (horizon_s or wall_s) else 0,
        "wall_s": round(wall_s, 3),
        "completed": len(completed),
        "abandoned": len(walked),
        "shed_429": len(shed_429),
        "shed_503": len(shed_503),
        "deadline_misses": len(misses),
        "errors": len(errors),
        "error_codes": sorted({o.code for o in errors if o.code})[:8],
        "throughput_tokens_per_sec": round(toks / wall_s, 2)
        if wall_s else 0.0,
        "p50_e2e_ms": round(_pct(lat_ms, 50) or 0.0, 1),
        "p99_e2e_ms": round(_pct(lat_ms, 99) or 0.0, 1),
        "p99_ttft_ms": round((_pct(_metric_values("ttft", completed),
                                   99) or 0.0) * 1e3, 1),
        "p99_tpot_ms": round((_pct(_metric_values("tpot", completed),
                                   99) or 0.0) * 1e3, 1),
        "slo": slo_rows,
        "slo_attainment": round(attained_n / len(policy), 4)
        if policy else None,
        "goodput": len(good),
        "goodput_fraction": round(len(good) / demanded, 4)
        if demanded else 0.0,
        "goodput_rps": round(len(good) / wall_s, 3) if wall_s else 0.0,
        "outcomes": outcomes,
    }


def occupancy_summary(n: int = 512,
                      since_ms: Optional[float] = None) -> dict:
    """Reduce the graftscope occupancy series (the same rings
    /debug/profile serves) to per-series {points, max, mean} — queue
    depth, batch occupancy, pool blocks, breaker state. ``since_ms``
    (a ``graftscope.now_ms`` instant) windows the reduction to points
    sampled after it — run_load passes its own start, so sequential
    runs against one app (warmup, a Pareto sweep) don't bleed each
    other's spikes into per-run columns. None = whole ring."""
    series = graftscope.snapshot(n=n).get("series", {})
    out: Dict[str, dict] = {}
    for label, pts in sorted(series.items()):
        if not any(label.startswith(name) for name in OCCUPANCY_SERIES):
            continue
        values = [v for t, v in pts
                  if since_ms is None or t >= since_ms]
        if not values:
            continue
        out[label] = {"points": len(values),
                      "max": round(max(values), 3),
                      "mean": round(sum(values) / len(values), 3)}
    return out


def pareto_row(report: dict) -> dict:
    """The compact Pareto point bench.py journals per (profile, rate):
    offered rate -> achieved throughput + tails + shed split."""
    keep = ("profile", "rate_scale", "offered", "offered_rps",
            "completed", "abandoned", "shed_429", "shed_503",
            "deadline_misses", "errors", "throughput_tokens_per_sec",
            "p50_e2e_ms", "p99_e2e_ms", "p99_ttft_ms", "p99_tpot_ms",
            "goodput_rps", "goodput_fraction")
    return {k: report[k] for k in keep}


def slo_row(report: dict) -> dict:
    """The compact SLO-attainment row bench.py journals per profile."""
    keep = ("profile", "rate_scale", "offered", "completed",
            "abandoned", "shed_429", "shed_503", "deadline_misses",
            "slo", "slo_attainment", "goodput", "goodput_fraction",
            "goodput_rps")
    return {k: report[k] for k in keep}


def traffic_mix_row(reports: List[dict]) -> dict:
    """The measured TRAFFIC-MIX signal (the ROADMAP item-5/6 follow-on
    AUTO_PLAN continuous mode consumes): one row per (profile,
    rate_scale) run joining the demand side (offered rate), the value
    side (goodput under the declared SLOs), and the occupancy the mix
    induced inside the serving stack (queue depth, batch occupancy,
    pool blocks — each run's own windowed graftscope reduction). This
    is exactly the tuple a live re-planner watches to decide the
    measured optimum flipped: journaled by bench.py as the
    ``traffic_mix`` row and gated by tools/bench_diff.py
    (goodput/throughput higher-better, queue depth lower-better)."""
    rows = []
    for rep in reports:
        occ = rep.get("occupancy", {})

        def _mean_of(prefix: str, occ=occ) -> Optional[float]:
            vals = [v["mean"] for k, v in occ.items()
                    if k.startswith(prefix)]
            return (round(sum(vals) / len(vals), 3) if vals else None)

        rows.append({
            "workload": f"{rep['profile']}_x{rep['rate_scale']:g}"
                        .replace(".", "p"),
            "profile": rep["profile"],
            "rate_scale": rep["rate_scale"],
            "offered_rps": rep["offered_rps"],
            "completed": rep["completed"],
            "throughput_tokens_per_sec":
                rep["throughput_tokens_per_sec"],
            "goodput_rps": rep["goodput_rps"],
            "goodput_fraction": rep["goodput_fraction"],
            "shed_429": rep["shed_429"],
            "shed_503": rep["shed_503"],
            "deadline_misses": rep["deadline_misses"],
            "mean_queue_depth": _mean_of("queue_depth"),
            "mean_batch_occupancy": _mean_of("batch_occupancy"),
            "mean_blocks_in_use": _mean_of("kv_cache_blocks_in_use"),
        })
    return {"workloads": rows}
