"""graftload: seeded open-loop load generation + declared SLO contracts.

The load-level observability layer (ROADMAP item 6), in the spine's
static+dynamic split:

- **dynamic half** (this package + ``python -m tools.graftload``): a
  seeded OPEN-LOOP load generator whose schedule is a pure function of
  ``(seed, profile, k)`` — replay-identical like ``FaultPlan`` and
  GRAFTSCHED schedules — driving the real in-process serving app
  through composable workload profiles (``profiles.PROFILES``) while
  the graftscope occupancy series record queue depth, batch occupancy,
  pool blocks, and breaker state;
- **static half** (``tools/graftcheck/slo.py``): SLOs are a DECLARED
  contract — every profile declares ``SLO_POLICY = {metric: (target,
  percentile)}`` and the slo pass verifies each target is computable
  from a ``METRIC_CATALOG`` series the request path actually emits.

Per-run output: throughput-vs-p99 Pareto rows and goodput-under-SLO
(typed 429/503 sheds counted separately from SLO misses), journaled by
``bench.py`` as ``graftload_pareto`` / ``slo_attainment`` and gated by
``tools/bench_diff.py`` like any other row.
"""

from .driver import (Outcome, occupancy_summary, pareto_row,  # noqa: F401
                     run_load, slo_row, summarize, traffic_mix_row)
from .profiles import (PROFILES, SLO_METRICS, SLO_POLICY,  # noqa: F401
                       SLO_SOURCE_METRICS, WorkloadProfile, profile,
                       slo_for)
from .schedule import (Arrival, arrival_fields, schedule,  # noqa: F401
                       schedule_bytes, shared_prefix)

__all__ = [
    "Arrival", "Outcome", "PROFILES", "SLO_METRICS", "SLO_POLICY",
    "SLO_SOURCE_METRICS", "WorkloadProfile", "arrival_fields",
    "occupancy_summary", "pareto_row", "profile", "run_load",
    "schedule", "schedule_bytes", "shared_prefix", "slo_for",
    "slo_row", "summarize", "traffic_mix_row",
]
