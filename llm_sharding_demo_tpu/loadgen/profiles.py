"""graftload workload profiles + declared SLO contracts.

A *profile* is a composable description of one production traffic
shape: how requests arrive (open-loop rate process), what they look
like (prompt length, shared-prefix structure, decode budget), and how
callers behave (deadline budgets, mid-stream abandonment). The load
schedule derived from a profile is a pure function of ``(seed,
profile, k)`` (``loadgen.schedule``) — the same replay-identity
contract as ``FaultPlan`` and GRAFTSCHED schedules — so a load run is
a pinnable artifact, not a dice roll.

SLOs are a DECLARED contract (the graftcheck ``slo`` pass is the
static half, ``tools/graftcheck/slo.py``): every profile in
``PROFILES`` declares an ``SLO_POLICY`` entry ``{metric: (target,
percentile)}`` over the fixed vocabulary

- ``ttft``          — time to first token, seconds; attained when the
                      declared percentile of completed requests lands
                      at or under ``target``;
- ``tpot``          — time per output token (inter-token), seconds,
                      same percentile semantics;
- ``e2e``           — whole-request wall time, seconds, same
                      percentile semantics (this is also the budget
                      "goodput under SLO" counts against);
- ``deadline_miss`` — fraction of demanded requests that die on their
                      deadline budget (typed 503 ``deadline_exceeded``);
                      ``target`` is the maximum tolerated fraction and
                      the percentile slot is fixed at 100 (a rate cap,
                      not a distribution point).

``SLO_SOURCE_METRICS`` maps each vocabulary metric to the
``METRIC_CATALOG`` series the serving request path actually emits —
the slo pass verifies every declared target is computable from a
metric that really exists and is really emitted, so an SLO can never
reference a number nobody measures (``slo-without-source-metric``),
and every profile carries a policy (``profile-without-slo``).

Typed sheds (429 pool-admission, 503 breaker/park-budget) are NOT SLO
misses: a shed is the system refusing work honestly, a miss is the
system accepting work and failing the promise. ``loadgen.driver``
counts them separately and ``goodput`` only charges the latter.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

# The fixed SLO metric vocabulary (the slo pass rejects anything else).
SLO_METRICS = ("ttft", "tpot", "e2e", "deadline_miss")

# vocabulary metric -> the METRIC_CATALOG series the request path emits
# it from (tools/graftcheck/slo.py verifies both the catalog entry and
# a live emission site; see utils/metrics.py METRIC_CATALOG).
SLO_SOURCE_METRICS = {
    "ttft": "ttft_seconds",
    "tpot": "tpot_seconds",
    "e2e": "generate_request_seconds",
    "deadline_miss": "deadline_misses_total",
}


@dataclasses.dataclass(frozen=True)
class WorkloadProfile:
    """One declared traffic shape. All rates are at scale 1.0 against
    the tiny bench/test model; drivers scale with ``rate_scale``."""

    name: str
    description: str
    # arrival process: "poisson" (memoryless open loop) or "bursty"
    # (burst-start gaps at rate/burst, near-zero intra-burst gaps —
    # the arrival clumping that makes closed-loop generators lie)
    arrival: str = "poisson"
    rate_rps: float = 4.0
    burst: int = 1                     # mean burst size (bursty only)
    prompt_len: Tuple[int, int] = (8, 24)
    max_new: Tuple[int, int] = (8, 16)
    # shared-prefix structure: each request's prompt starts with one of
    # ``prefix_pool`` deterministic shared prefixes of
    # ``shared_prefix_len`` chars (0 = no shared structure). Deep
    # shared prefixes exercise the prefix store + CoW machinery.
    shared_prefix_len: int = 0
    prefix_pool: int = 1
    # prefix depth: > 0 overrides prefix_pool as the count of distinct
    # deterministic shared prefixes the schedule draws from — the
    # grafttier driver, letting a run touch a prefix population deeper
    # than the device pool can hold so cold entries demote to the host
    # tier. 0 keeps the prefix_pool draw: schedules are byte-identical
    # to before the knob existed (replay purity pin).
    prefix_depth: int = 0
    # cache busting: every request gets a UNIQUE leading prefix, so any
    # content-keyed reuse (prefix store) whiffs by construction
    cache_busting: bool = False
    # caller behavior: an optional X-Deadline-Ms budget on every
    # request, and a fraction of requests that "walk away" mid-stream
    # by carrying ``abandon_after_ms`` as their budget instead (the
    # graftfault deadline-cancellation boundary: the row is cancelled
    # at the next segment boundary with its blocks freed)
    deadline_ms: Optional[int] = None
    abandon_rate: float = 0.0
    abandon_after_ms: int = 40
    mode: str = "greedy"               # greedy keeps replay byte-exact


# The profile registry the slo pass reads (dict literal on purpose:
# the keys are statically visible to tools/graftcheck/slo.py, exactly
# like FAULT_POLICY / GUARDED_STATE declarations).
PROFILES = {
    "bursty_chat": WorkloadProfile(
        name="bursty_chat",
        description="chat bursts over deep shared system prompts "
                    "(prefix store + CoW exercise; arrival clumping)",
        arrival="bursty", rate_rps=6.0, burst=4,
        prompt_len=(24, 48), max_new=(8, 16),
        shared_prefix_len=20, prefix_pool=3),
    "long_context": WorkloadProfile(
        name="long_context",
        description="long-context summarization: big prompts, short "
                    "answers (prefill-dominated, pool-block heavy)",
        arrival="poisson", rate_rps=1.5,
        prompt_len=(96, 160), max_new=(4, 8),
        shared_prefix_len=32, prefix_pool=2),
    "agentic": WorkloadProfile(
        name="agentic",
        description="agent loops: many short turns at high rate "
                    "(queueing + admission churn)",
        arrival="poisson", rate_rps=10.0,
        prompt_len=(4, 12), max_new=(4, 8),
        shared_prefix_len=8, prefix_pool=2),
    "abandonment": WorkloadProfile(
        name="abandonment",
        description="mid-stream abandonment: a slice of callers walk "
                    "away on a short deadline budget (segment-boundary "
                    "cancellation + block reclamation under load)",
        arrival="poisson", rate_rps=5.0,
        prompt_len=(12, 32), max_new=(12, 24),
        deadline_ms=60_000, abandon_rate=0.3, abandon_after_ms=40),
    "cache_buster": WorkloadProfile(
        name="cache_buster",
        description="adversarial cache-busting prompts: unique "
                    "prefixes defeat content-keyed reuse, every "
                    "request pays a cold prefill",
        arrival="poisson", rate_rps=4.0,
        prompt_len=(16, 40), max_new=(8, 16),
        cache_busting=True),
}

# Declared SLO contracts, one entry per profile (the slo pass fails a
# profile without one, a stale entry for a dead profile, and any
# metric outside SLO_METRICS / outside SLO_SOURCE_METRICS). Targets
# are seconds (fractions for deadline_miss) against the tiny CPU test
# model — deliberately loose: the contract these pin is the SHAPE of
# the promise (which metrics, which percentiles); tightening targets
# per deployment is a config edit, not a code change.
SLO_POLICY = {
    "bursty_chat": {"ttft": (5.0, 95), "tpot": (1.0, 95),
                    "e2e": (60.0, 99)},
    "long_context": {"ttft": (20.0, 95), "e2e": (120.0, 99)},
    "agentic": {"ttft": (2.5, 95), "tpot": (1.0, 95),
                "e2e": (30.0, 99)},
    "abandonment": {"e2e": (60.0, 99), "deadline_miss": (0.05, 100)},
    "cache_buster": {"ttft": (10.0, 95), "e2e": (90.0, 99)},
}


def profile(name: str) -> WorkloadProfile:
    try:
        return PROFILES[name]
    except KeyError:
        raise KeyError(f"unknown workload profile {name!r}; registered: "
                       f"{sorted(PROFILES)}") from None


def slo_for(name: str) -> dict:
    """The declared SLO policy for a profile (the slo pass guarantees
    this lookup cannot miss for a registered profile)."""
    return SLO_POLICY[name]
