"""Seeded open-loop arrival schedules: pure functions of (seed, profile, k).

The generator is OPEN-LOOP: arrival k fires at its scheduled offset
``t_k`` whether or not earlier requests finished. A closed-loop
generator (N workers, next request only after the previous returns)
throttles itself exactly when the system saturates, so its measured
p99 silently excludes the queueing collapse real users would feel —
tests/test_graftload.py pins that under-report against this module.

Replay identity (the FaultPlan / GRAFTSCHED contract): every field of
arrival k — its inter-arrival gap, prompt text, decode budget,
deadline, abandonment flag — is drawn from ``random.Random(f"{seed}/
{name}/{k}")``, so the k-th arrival is a pure function of ``(seed,
profile, k)`` and two schedules built from the same seed are
byte-identical (``schedule_bytes`` is the pinnable serialization).
``t_k`` is the running sum of the per-k gaps — still pure in
``(seed, profile, k)``, computed once per schedule.

Prompts are ascii text (the serving wire unit); shared prefixes are
deterministic per ``(profile, prefix_id)`` — NOT per seed — so two
different load seeds still hit the same prefix-store entries, the way
real system prompts behave across traffic.
"""

from __future__ import annotations

import dataclasses
import json
import random
import string
from typing import List, Optional

from .profiles import WorkloadProfile

_ALPHABET = string.ascii_lowercase + "    "   # spaces keep text wordy


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled request (the open-loop unit of work)."""

    k: int                      # arrival index within the run
    t: float                    # seconds from run start (open-loop)
    prompt: str
    max_new: int
    mode: str
    seed: int                   # per-request sampling seed (wire field)
    deadline_ms: Optional[int]  # X-Deadline-Ms budget, None = none
    abandoned: bool             # True: deadline IS the walk-away budget

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def shared_prefix(profile: WorkloadProfile, prefix_id: int) -> str:
    """The deterministic shared prefix ``prefix_id`` of a profile —
    seed-independent, so distinct load runs share store entries."""
    rng = random.Random(f"prefix/{profile.name}/{prefix_id}")
    return "".join(rng.choice(_ALPHABET)
                   for _ in range(profile.shared_prefix_len))


def _gap(profile: WorkloadProfile, rng: random.Random,
         rate_scale: float) -> float:
    """Inter-arrival gap BEFORE arrival k (arrival 0 fires at t=0)."""
    rate = max(profile.rate_rps * rate_scale, 1e-6)
    if profile.arrival == "bursty" and profile.burst > 1:
        # geometric burst membership: roughly 1/burst of arrivals start
        # a new burst (gap at the burst rate), the rest pile in behind
        # it — the clumping that stresses admission and queue depth
        if rng.random() < 1.0 / profile.burst:
            return rng.expovariate(rate / profile.burst)
        return 0.002
    return rng.expovariate(rate)


def arrival_fields(profile: WorkloadProfile, seed: int, k: int,
                   rate_scale: float = 1.0) -> dict:
    """Every draw for arrival k (gap included) — THE pure function.
    ``schedule`` only accumulates gaps into offsets."""
    rng = random.Random(f"{seed}/{profile.name}/{k}")
    gap = 0.0 if k == 0 else _gap(profile, rng, rate_scale)
    plen = rng.randint(*profile.prompt_len)
    parts = []
    if profile.cache_busting:
        # unique leading bytes: content-keyed reuse whiffs on purpose
        parts.append(f"bust-{seed}-{k}-")
    elif profile.shared_prefix_len > 0:
        # prefix_depth > 0 widens the draw past prefix_pool so a run
        # can touch more distinct prefixes than the device pool holds
        # (the grafttier spill driver); 0 keeps the historical draw,
        # and either way it is ONE randrange call so the rest of the
        # per-arrival draw sequence is byte-identical (replay pin).
        parts.append(shared_prefix(
            profile,
            rng.randrange(profile.prefix_depth
                          or max(profile.prefix_pool, 1))))
    need = max(plen - sum(len(p) for p in parts), 1)
    parts.append("".join(rng.choice(_ALPHABET) for _ in range(need)))
    abandoned = rng.random() < profile.abandon_rate
    deadline_ms = (profile.abandon_after_ms if abandoned
                   else profile.deadline_ms)
    return {
        "gap": gap,
        "prompt": "".join(parts),
        "max_new": rng.randint(*profile.max_new),
        "mode": profile.mode,
        "seed": rng.randrange(2 ** 31),
        "deadline_ms": deadline_ms,
        "abandoned": abandoned,
    }


def schedule(profile: WorkloadProfile, seed: int, n: int,
             rate_scale: float = 1.0) -> List[Arrival]:
    """The first ``n`` arrivals of ``(seed, profile)`` at
    ``rate_scale`` x the profile's declared rate. Replay-identical:
    same arguments, byte-identical schedule (pinned)."""
    out: List[Arrival] = []
    t = 0.0
    for k in range(n):
        f = arrival_fields(profile, seed, k, rate_scale)
        t += f.pop("gap")
        out.append(Arrival(k=k, t=round(t, 9), **f))
    return out


def schedule_bytes(profile: WorkloadProfile, seed: int, n: int,
                   rate_scale: float = 1.0) -> bytes:
    """Canonical serialization of the schedule — what the replay pin
    compares byte-for-byte."""
    rows = [a.to_dict() for a in schedule(profile, seed, n, rate_scale)]
    return json.dumps(rows, sort_keys=True,
                      separators=(",", ":")).encode()
