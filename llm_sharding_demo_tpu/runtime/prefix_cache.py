"""Prefix caching: cross-request KV reuse for shared prompt prefixes.

Serving workloads repeat prompt prefixes constantly (system prompts,
few-shot preambles, chat history). The reference re-forwards every token
of every request (reference server.py:169-181); the plain engine prefills
each request from scratch. This front end caches KV states at chunk
boundaries and, on a prefix hit, prefills only the suffix.

Design — right-aligned chunking, unlike the engine's left-padded
``prefill_chunk``:

- The prompt is split from the LEFT edge into ``chunk``-wide pieces plus
  a ragged tail. Positions are true absolute positions (no pad), so a
  prefix's KV state is identical no matter what follows it — exactly the
  property left-alignment destroys (its pad width depends on total
  length) and the reason this module does its own chunking.
- Compile count stays bounded: one program for the chunk width + at most
  ``chunk - 1`` tail widths, regardless of prompt length diversity.
- Cache entries are keyed by the token *content* of the first ``m``
  chunks and stored in LRU order. A lookup walks from the longest
  possible prefix down, so a request reuses the deepest cached state
  available, then extends it chunk by chunk.
- Exactness: a hit replays the same ``forward_with_cache`` math the cold
  path runs, on a device-side COPY of the stored buffers (the decode
  scan donates its cache input, and stored entries must survive), so
  greedy streams are byte-identical with the cache on or off — pinned by
  tests/test_prefix_cache.py.

Single-stream by design (per-row cache depths would need per-row offsets,
like speculation); ``runtime.batcher`` remains the batched-throughput
path. Thread-safe: ThreadingHTTPServer handles requests concurrently and
the store + donation-sensitive programs are serialized by a lock.

What a hit saves is prefill COMPUTE and HBM traffic (a 3092-token prompt
with a 3072-token cached prefix forwards 148 tokens instead of 3092 —
~20x less device work, measured equal-dispatch-count with the plain
prefill). On the tunneled bench chip, wall-clock prefill is dominated by
the fixed ~100 ms host<->device sync, so the win appears as freed device
time/HBM rather than lower request latency; on a locally attached chip
(or under load, where device time is the contended resource) it is both.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import graftmem, graftsched, graftscope, tracing
from ..utils.metrics import REGISTRY
from .engine import (DecodeEngine, GenerateResult, SamplingConfig,
                     prepare_generate, select_token)

# Static-analysis contract (tools/graftcheck): every ``jax.jit`` site in
# this module, by holding attribute — an undeclared site is a lint
# finding (a compiled-program population the recompile budget would
# silently miss).
JIT_ENTRY_POINTS = ("_extend", "_extend_keep")

# Observability contract (tools/graftcheck scope pass + utils/graftscope):
# both continuation programs' dispatches are timed into the graftscope
# ring (graftscope.instrument at the jit sites), keyed by operand shape
# — the ids width IS the program key (one program per chunk/tail width).
PROFILED_SCOPES = ("_extend", "_extend_keep")


def _extend_scope_key(params, cache, ids):
    return (int(ids.shape[0]), int(ids.shape[1]))

# Donation contract (tools/graftcheck sanitize pass): ``_extend``
# consumes its cache input (arg 1 — fresh caches and intermediate walk
# states); ``_extend_keep`` deliberately does NOT (stored entries must
# survive their first replay) and so declares nothing.
DONATED_ARGS = {"_extend": (1,)}

# Pool-mover lease scopes (tools/graftcheck sanitize pass): the store's
# two pool touchpoints — both move only block ids they hold refs on
# (the lookup's caller refs / the insert's fresh allocation).
POOL_MOVER_SCOPES = ("PrefixCachingEngine._gather_entry",
                     "PrefixCachingEngine._insert_pool")

# Registry handoff scopes (tools/graftcheck fleet pass): the ONLY
# functions allowed to touch the allocator's content-keyed registry
# surface (``lookup_prefix`` / ``register_prefix``) — the prefill ->
# decode block-handoff boundary. ``_lookup`` takes the adopter-side
# caller refs (a decode row referencing a prefill replica's blocks),
# ``_insert_pool`` registers the producer side (the registry takes its
# own refs). Enumerating the boundary here is what lets graftsan's
# per-block grant provenance be read as HANDOFF provenance: every
# cross-replica block lease traces to one of these two declared sites.
HANDOFF_SCOPES = ("PrefixCachingEngine._lookup",
                  "PrefixCachingEngine._insert_pool")

# Tier-movement contract (tools/graftcheck tier pass): the store's two
# grafttier touch points — the depth walk promotes a demoted entry on
# an affinity hit, and the capacity trim demotes the device LRU before
# falling back to plain eviction.
SPILL_SCOPES = ("PrefixCachingEngine._lookup",
                "PrefixCachingEngine._insert_pool")

# HBM-ledger contract (tools/graftcheck memory pass + utils/graftmem):
# the store's deep-copied cache pytrees (non-pool mode) are the
# module's long-lived device holdings — one handle-keyed ledger entry
# per stored prefix, registered at insert and released at LRU eviction.
# Pool-mode entries are block-id tuples (host ints, refs on the pool's
# own ledgered plane), so nothing registers and nothing double-counts.
MEMORY_LEDGER = {
    "_store": "prefix_store",
}

# Growth-bound contract (tools/graftcheck unbounded-device-growth
# rule): the store accumulates device arrays but is bounded — at most
# ``capacity`` entries, LRU ``popitem(last=False)`` eviction at insert.
MEMORY_BOUNDS = {
    "_store": "capacity entries; LRU popitem(last=False) at insert",
}

# Lock-discipline contract (tools/graftcheck locks pass): the store and
# its hit/miss counters live under ``_store_lock`` only — ``stats()``
# (the /healthz read) must never wait out an in-flight generation's
# seconds of device time behind the big lock.
GUARDED_STATE = {"_store": "_store_lock", "hits": "_store_lock",
                 "misses": "_store_lock", "_mem_handles": "_store_lock"}

# The device lock is always the OUTER of the pair (generate/prefill
# take ``_lock``, then the walk touches the store under
# ``_store_lock``); an opposite-order path would deadlock a /healthz
# reader against an in-flight generation.
LOCK_ORDER = ("_lock", "_store_lock")

# ``_lock`` serializes the donation-sensitive extend/decode programs —
# one generation at a time is the module's documented design, so device
# dispatch under it is not a blocking-under-lock finding.
DEVICE_LOCKS = ("_lock",)


class PrefixCachingEngine:
    """Wraps a ``DecodeEngine`` with a chunk-aligned KV prefix cache.

    ``capacity`` bounds resident entries (each is a full
    ``[L, 1, H, max_seq, hd]`` KV buffer pair in the engine dtype — size
    the capacity to HBM). ``chunk`` is the alignment width: prefixes are
    cached at multiples of it, and it bounds the compile count of the
    incremental prefill programs.
    """

    def __init__(self, engine: DecodeEngine, capacity: int = 4,
                 chunk: int = 64, spec=None, pool=None):
        """``spec`` (optional ``SpecDecodeEngine`` wrapping THIS
        ``engine``) composes speculation with prefix reuse: the prefix
        path builds the cache, the verify loop decodes off it. Requests
        speculation can't serve (short prompts, no draft headroom) fall
        back to the plain decode scan.

        ``pool`` (optional ``runtime.kv_pool.KVBlockPool`` matching THIS
        engine's cache geometry) re-homes the store into the shared
        block pool: entries hold ref-counted BLOCK IDS instead of full
        ``[L, 1, H, max_seq, hd]`` buffer copies, so (a) an entry costs
        ``ceil(depth / block_size)`` blocks, not a whole ``max_seq``
        allocation, (b) entries that extend each other SHARE their
        common chunks' physical blocks structurally (the entry for
        chunks [0, m) and the deeper [0, m+k) entry reference the same
        blocks — the old store stored both in full), (c) eviction is
        the allocator's LRU over zero-ref prefix blocks (pool pressure
        evicts cold entries even below ``capacity``), and (d) live
        paged decode rows can reference entry blocks directly
        (``prefill_shared`` — zero-copy reuse, the partially-filled
        frontier block CoW'd by the consumer). Byte-exactness is
        unchanged: a hit gathers the entry into a fresh contiguous
        buffer and replays the same extend programs."""
        from ..models import is_window_independent
        if not is_window_independent(engine.config):
            # same routing-semantics gate as speculation and chunked
            # prefill (see models.is_window_independent): a chunked
            # continuation off a cached prefix must route identically to
            # the monolithic prefill for byte-exactness to hold
            raise NotImplementedError(
                "prefix caching replays the prompt in chunk windows; MoE "
                "capacity-factor routing is window-dependent, so the "
                "cached path would not be token-exact — serve MoE with "
                "the plain engine")
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if chunk < 1:
            raise ValueError("chunk must be >= 1")
        if spec is not None and spec.plain is not engine:
            raise ValueError("spec must wrap the same DecodeEngine (shared "
                             "weights/programs), got a different instance")
        if pool is not None and pool.max_seq != engine._cache_seq:
            raise ValueError(
                f"pool rows span {pool.max_seq} slots, engine cache is "
                f"{engine._cache_seq}; gathered entries must match the "
                "extend programs' cache width")
        self._eng = engine
        self._spec = spec
        self._pool = pool
        self.capacity = capacity
        self.chunk = chunk
        self._store: "OrderedDict[Tuple[int, ...], object]" = OrderedDict()
        # store key -> graftmem handle for the entry's device bytes
        # (non-pool mode; empty under a pool)
        self._mem_handles: dict = {}
        # Two locks: ``_lock`` serializes device work (the donation-
        # sensitive extend/decode programs run one generation at a time),
        # while ``_store_lock`` guards only the store and counters — so
        # ``stats()`` (the /healthz read) never waits out an in-flight
        # generation's seconds of device time behind the big lock.
        self._lock = graftsched.lock("prefix_cache.PrefixCachingEngine._lock",
                                     timeout=600.0)
        self._store_lock = graftsched.lock(
            "prefix_cache.PrefixCachingEngine._store_lock")
        self.hits = 0
        self.misses = 0
        # One continuation program per ids width (the chunk width plus the
        # ragged tail widths < chunk): forward `ids` at cache.length.
        # Two donation variants: ``_extend`` consumes its cache input
        # (fresh caches and intermediate states), while ``_extend_keep``
        # leaves it intact — used for the FIRST step off a stored entry,
        # so the "copy the stored buffers" happens INSIDE the program
        # (XLA's copy-on-update of a non-donated input) instead of as a
        # separate host-dispatched copy. On a tunneled chip each dispatch
        # costs ~100 ms of sync — folding the copy keeps a full-depth hit
        # at the same dispatch count as a plain prefill.
        def _run(params, cache, ids):
            return engine._forward_cached(params, ids, cache, None)

        self._extend = graftscope.instrument(
            jax.jit(_run, donate_argnums=(1,)), "prefix_cache._extend",
            key_fn=_extend_scope_key)
        self._extend_keep = graftscope.instrument(
            jax.jit(_run), "prefix_cache._extend_keep",
            key_fn=_extend_scope_key)

    @property
    def plain(self) -> DecodeEngine:
        return self._eng

    @staticmethod
    def _key(prompt: np.ndarray, m_chunks: int, chunk: int) -> bytes:
        """Exact, cheap store key: the raw int32 bytes of the first
        ``m_chunks`` chunks (no per-token Python boxing — lookups on
        long prompts walk many candidate depths under the lock)."""
        return np.ascontiguousarray(
            prompt[:m_chunks * chunk], dtype=np.int32).tobytes()

    def _lookup(self, prompt: np.ndarray) -> Tuple[int, Optional[object]]:
        """Longest cached prefix of ``prompt`` -> (n_chunks_hit, entry).
        Non-pool entries are stored cache pytrees; pool entries are
        block-id tuples with one caller ref added per block (release
        with ``allocator.free``)."""
        m_max = (len(prompt) - 1) // self.chunk  # leave >=1 token to forward
        if self._pool is not None:
            tier = self._pool.tier
            for m in range(m_max, 0, -1):
                key = self._key(prompt, m, self.chunk)
                ids = self._pool.allocator.lookup_prefix(key)
                if ids is None and tier is not None and tier.has(key):
                    # demoted entry (grafttier): promote its blocks back
                    # into the pool ahead of admission. The entry kept
                    # its content key through the round trip, so the
                    # zero-copy reference semantics downstream
                    # (prefill_shared re-walking this very key) hold
                    # unchanged; a refused promote (pool full even
                    # after demoting) just walks on to shallower depths.
                    ids = tier.promote(self._pool, key)
                if ids is not None:
                    return m, ids
            return 0, None
        with self._store_lock:
            for m in range(m_max, 0, -1):
                key = self._key(prompt, m, self.chunk)
                entry = self._store.get(key)
                if entry is not None:
                    self._store.move_to_end(key)
                    return m, entry
        return 0, None

    def _gather_entry(self, ids, depth: int):
        """Pool mode: assemble an entry's blocks into a fresh
        contiguous full-width cache (trash-padded past the entry, where
        every slot is masked anyway) — byte-equal to the stored state,
        and safely donatable by the extend/decode programs."""
        import numpy as _np
        table = _np.full((1, self._pool.nbm), self._pool.trash,
                         dtype=_np.int32)
        table[0, :len(ids)] = ids
        return self._pool.gather(table, depth)

    def _insert_pool(self, prompt: np.ndarray, m_total: int, cache,
                     hit_ids, m_hit: int) -> None:
        """Pool-mode insert: the new entry SHARES the hit entry's full
        blocks and allocates fresh ones only for the new chunks (the
        frontier region is re-scattered from the walk cache into a
        fresh block — registry blocks stay immutable). A full pool
        skips the insert instead of failing the request."""
        from .kv_pool import PoolExhausted
        alloc = self._pool.allocator
        key = self._key(prompt, m_total, self.chunk)
        if alloc.has_prefix(key):
            return
        bs = self._pool.block_size
        nb_new = alloc.blocks_for(m_total * self.chunk)
        n_share = (m_hit * self.chunk) // bs if hit_ids else 0
        share = list(hit_ids[:n_share]) if hit_ids else []
        try:
            fresh = alloc.alloc(nb_new - n_share)
        except PoolExhausted:
            return
        try:
            table = np.full((1, self._pool.nbm), self._pool.trash,
                            dtype=np.int32)
            table[0, :n_share] = share
            table[0, n_share:nb_new] = fresh
            self._pool.scatter_columns(cache, table, n_share)
            alloc.register_prefix(key, share + fresh)
        finally:
            alloc.free(fresh)  # entry refs (if registered) keep them;
            # on a scatter/register failure this is the leak guard
        while alloc.prefix_len() > self.capacity:
            # capacity trim prefers the tier ladder: demote the LRU
            # entry to host RAM when a grafttier is attached, and only
            # evict to oblivion when there is no tier (or it refused —
            # budget exhausted / race)
            tier = self._pool.tier
            if tier is None or not tier.demote_lru(self._pool):
                alloc.evict_lru()

    def _insert(self, prompt: np.ndarray, m_chunks: int, cache) -> None:
        """Store a COPY of ``cache`` as the state after ``m_chunks`` full
        chunks of ``prompt`` (no-op if present)."""
        if m_chunks < 1:
            return
        key = self._key(prompt, m_chunks, self.chunk)
        with self._store_lock:
            if key in self._store:
                self._store.move_to_end(key)
                return
            entry = jax.tree.map(jnp.copy, cache)
            self._store[key] = entry
            self._mem_handles[key] = graftmem.track(
                self, "_store", "prefix_store", entry)
            while len(self._store) > self.capacity:
                old, _ = self._store.popitem(last=False)
                graftmem.release(self._mem_handles.pop(old, 0))

    def _prefill_walk(self, prompt: np.ndarray, prompt_len: int):
        """Store-aware chunk-aligned prefill of one prompt row: returns
        ``(last_logits [1, V], cache)``. Caller holds ``self._lock``.

        The returned cache is always a fresh program output (the tail
        step runs unconditionally and the first step off a stored entry
        copies inside the program, ``_extend_keep``), so downstream
        decode may donate it."""
        run_params = self._eng._run_params()
        m_hit, entry = self._lookup(prompt)
        hit_ids = None
        if entry is not None:
            with self._store_lock:
                self.hits += 1
            REGISTRY.inc("prefix_cache_hits_total")
            REGISTRY.inc("prefix_cache_reused_tokens_total",
                         value=m_hit * self.chunk)
            # mark the enclosing prefill span (request trace) so a
            # flight-recorder timeline shows hit depth, not just speed
            tracing.annotate_span(prefix_hit=True,
                                  reused_tokens=m_hit * self.chunk)
            if self._pool is not None:
                hit_ids = entry                 # ref'd block ids
                try:
                    cache = self._gather_entry(hit_ids,
                                               m_hit * self.chunk)
                except BaseException:
                    self._pool.allocator.free(hit_ids)
                    raise
            else:
                cache = entry
        else:
            with self._store_lock:
                self.misses += 1
            REGISTRY.inc("prefix_cache_misses_total")
            tracing.annotate_span(prefix_hit=False)
            cache = self._eng._fresh_cache(1)

        # extend chunk by chunk (one shared program), snapshotting the
        # deepest full-chunk state for the store before the ragged
        # tail consumes the buffers. The first step off a stored
        # entry must not donate it (see _extend_keep) — unless the
        # entry came from the pool, where the gather already produced
        # a fresh buffer.
        m_total = (prompt_len - 1) // self.chunk
        from_store = entry is not None and self._pool is None

        def step(cache, ids):
            nonlocal from_store
            fn = self._extend_keep if from_store else self._extend
            from_store = False
            return fn(run_params, cache, ids)

        try:
            logits = None
            for m in range(m_hit, m_total):
                piece = jnp.asarray(
                    prompt[None, m * self.chunk:(m + 1) * self.chunk])
                logits, cache = step(cache, piece)
            if m_total > m_hit:
                if self._pool is not None:
                    self._insert_pool(prompt, m_total, cache, hit_ids,
                                      m_hit)
                else:
                    self._insert(prompt, m_total, cache)
        finally:
            # the caller refs taken by the pool lookup must not outlive
            # the walk even when an extend step raises — a phantom ref
            # would pin the entry's blocks past its own eviction
            if hit_ids is not None:
                self._pool.allocator.free(hit_ids)
        tail = jnp.asarray(prompt[None, m_total * self.chunk:])
        logits, cache = step(cache, tail)
        return logits, cache

    def prefill_state(self, prompt: np.ndarray):
        """Public single-row prefill for the batching front end
        (runtime.batcher): ``(last_logits [1, V], cache, prompt_len)``
        with the store consulted/updated. The caller owns the returned
        cache (safe to donate)."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        with self._lock:
            with tracing.span("prefill", prefix=True,
                              prompt_len=len(prompt)):
                logits, cache = self._prefill_walk(prompt, len(prompt))
        return logits[:, -1], cache, len(prompt)

    def prefill_shared(self, prompt: np.ndarray):
        """Paged-runner entry (pool mode only): walk the store, then
        return ``(last_logits [1, V], cache, shared_ids, hit_depth)``
        where ``shared_ids`` are the block ids of the DEEPEST entry now
        covering the prompt (including one the walk just inserted),
        with one caller ref per block — the runner references them in
        its own table instead of duplicating the prefill state, and
        releases them at retirement."""
        if self._pool is None:
            raise ValueError("prefill_shared requires a pool-backed "
                             "store (pass pool= at construction)")
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        with self._lock:
            with tracing.span("prefill", prefix=True,
                              prompt_len=len(prompt)):
                logits, cache = self._prefill_walk(prompt, len(prompt))
            m, ids = self._lookup(prompt)
        return (logits[:, -1], cache, list(ids or ()),
                m * self.chunk)

    def generate(self, prompt_ids, max_new_tokens: int,
                 sampling: SamplingConfig = SamplingConfig(),
                 key: Optional[jax.Array] = None) -> GenerateResult:
        ids, batch, prompt_len, key, pad = prepare_generate(
            prompt_ids, max_new_tokens, self._eng.max_seq, sampling, key,
            allow_ragged=False)
        if batch != 1:
            raise ValueError("prefix caching is single-stream (batch=1); "
                             "batched throughput goes through "
                             "DecodeEngine / runtime.batcher")
        prompt = ids[0]
        run_params = self._eng._run_params()

        with self._lock:
            t0 = time.perf_counter()
            with tracing.span("prefill", prefix=True,
                              prompt_len=prompt_len):
                logits, cache = self._prefill_walk(prompt, prompt_len)

                prefill_key, decode_key = jax.random.split(key)
                first = select_token(logits[:, -1], sampling, prefill_key)
                first.block_until_ready()
            prefill_seconds = time.perf_counter() - t0

            spec = self._spec
            if spec is not None and spec.eligible(prompt_len,
                                                  max_new_tokens):
                # the prefix path's cache is right-aligned (no pad, true
                # positions, length == prompt_len) — exactly the state the
                # verify loop expects; it donates the cache, which is
                # always a fresh _extend output here (stored entries were
                # snapshotted by copy)
                result = spec.run_loop(
                    run_params, prompt, first, cache, prompt_len,
                    decode_key, max_new_tokens, sampling,
                    prefill_seconds=prefill_seconds)
            else:
                result = self._eng._decode_and_pack(
                    run_params, ids, pad, None, first, cache, decode_key,
                    max_new_tokens, sampling, prompt_len, prefill_seconds)
        return result

    def stats(self) -> dict:
        with self._store_lock:
            entries = (self._pool.allocator.prefix_len()
                       if self._pool is not None else len(self._store))
            out = {"entries": entries, "hits": self.hits,
                   "misses": self.misses, "capacity": self.capacity,
                   "chunk": self.chunk}
            if self._pool is not None:
                out["pooled"] = True
            return out
