"""Decode engine: jitted prefill + scanned token loop, on-device sampling.

TPU-native replacement for the reference's coordinator decode loop
(reference server.py:154-210), which per token: re-POSTs the *entire*
sequence over HTTP to shard A, relays hidden states to shard B, pulls fp32
logits back to the host as JSON, and samples in numpy/torch
(server.py:169-206). Here the whole generation is two compiled programs:

- ``prefill``: one forward over the prompt, filling the KV cache;
- ``decode``: a single ``lax.scan`` over ``max_new_tokens`` whose body is
  the cached single-token step + on-device token selection. No
  host↔device traffic inside the loop, no re-forwarding (the KV cache is
  the fix for the reference's O(n²) loop — BASELINE.json config 5).

Token selection modes mirror the reference:

- ``greedy``: argmax — BASELINE.json's parity mode.
- ``sample``: temperature + top-k multinomial, the reference's hard-coded
  temperature=0.6 / top_k=40 sampler (server.py:187-206) — but with an
  explicit PRNG key instead of torch's unseeded global state (SURVEY.md
  §2.3.4: cross-framework RNG parity is impossible; we mirror the
  distribution math).

Batching is a leading batch dim; unequal-length prompts left-pad into a
rectangle with per-row position offsets and key masks (``left_pad`` /
``prepare_generate`` — the reference hardcodes batch=1, server.py:137),
and ``runtime.batcher`` multiplexes concurrent serving requests onto
these batched decodes.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.gpt2 import GPT2Config, Params
from ..ops.attention import KVCache
from ..utils import graftmem, graftscope, tracing
from ..utils.metrics import REGISTRY, CompileWatch, kv_block_gauges

# Reference sampler constants (server.py:188, 191).
REF_TEMPERATURE = 0.6
REF_TOP_K = 40

# EOS-armed decodes check for stop every this many steps (a multiple of
# the segment planner's quantum, so capping mints no new programs).
EOS_SEGMENT = 32


# Static-analysis contract (tools/graftcheck): every ``jax.jit`` call
# site in this module must appear here, named by the attribute/function
# holding the jitted callable — the recompile-budget certifier
# enumerates these, and an undeclared jit site is a lint finding (a
# compiled-program population the budget would silently miss).
JIT_ENTRY_POINTS = ("_prefill", "_prefill_chunked", "_decode_seg")

# Observability contract (tools/graftcheck scope pass + utils/graftscope):
# every declared jit entry point whose dispatch is timed into the
# graftscope ring — wrapped in ``graftscope.instrument`` at the jit
# site, with a key_fn deriving the SAME program key the recompile
# certifier models, so measured rings join certified populations 1:1.
# An entry point neither listed here nor baselined with a justification
# is an ``unprofiled-entry-point`` finding.
PROFILED_SCOPES = ("_prefill", "_prefill_chunked", "_decode_seg")

# Donation contract (tools/graftcheck sanitize pass): the positional
# arguments each jitted entry point CONSUMES (donate_argnums). Callers
# must not re-read a donated buffer after the call, and any host view
# (np.asarray of a CPU jax array is zero-copy) of a value that flows
# into a donated slot must take an owning copy first — the
# donation-aliasing rules resolve call sites through this declaration.
DONATED_ARGS = {"_decode_seg": (2,)}

# Decode hot-loop scopes (tools/graftcheck host-sync rule): functions
# whose loop bodies sit between compiled decode dispatches, where an
# accidental ``.item()``/``np.asarray``/``float()`` on a device value
# stalls the dispatch pipeline. Intentional syncs are baselined in
# tools/graftcheck/baseline.txt with a justification.
GRAFTCHECK_HOT_LOOPS = ("DecodeEngine._decode_and_pack",)

# HBM-ledger contract (tools/graftcheck memory pass + utils/graftmem):
# the engine's long-lived device holdings, by graftmem component.
# ``params`` is the finalized weight tree — placed, quantized, or the
# staged slices (whichever copy the compiled programs actually read;
# registered once, AFTER mesh placement / stage partitioning settles
# which). ``cache`` is the contiguous decode working view: one ledger
# entry per in-flight ``_decode_and_pack`` (handle-keyed, so concurrent
# generates on one engine attribute independently), released where the
# last segment's output drops its alias on the donated prefill cache.
MEMORY_LEDGER = {
    "params": "params",
    "cache": "engine_cache",
}

# Numerics contract (tools/graftcheck numerics pass — the static half
# of graftnum): the engine's value-stream discipline. The compiled
# entry points carry the construction regime end to end (``carried``:
# params/cache/activations share ``self.dtype``, validated against
# graftnum.REGIMES in ``__init__`` with a typed error), and token
# selection runs f32 regardless of regime (``sampler_pmf`` upcasts the
# logits once — the "softmax and logits stay f32" half of the bf16/
# int8 prose, now traced). All entries exact: the f32 regime is the
# byte-pinned parity mode; approximate REGIMES are declared at their
# source modules (ops/quant.py -> decode.int8, ops/decode_layer.py ->
# decode.bf16) and measured by graftnum's oracle at the engine level.
PRECISION_CONTRACT = {
    "_prefill_impl": {"regime": "carried", "exact": True, "casts": ()},
    "_prefill_chunked_impl": {"regime": "carried", "exact": True,
                              "casts": ()},
    "_decode_seg_impl": {"regime": "carried", "exact": True,
                         "casts": ()},
    "sampler_pmf": {"regime": "f32", "exact": True, "casts": ("f32",)},
    "select_token": {"regime": "f32", "exact": True, "casts": ()},
}


# EOS check-cap doubling ceiling: checks land at 32, 64, 128, 256, 256...
# steps, so a long armed decode pays O(log) + steps/256 syncs instead of
# steps/32. On the tunneled bench chip a sync is ~100 ms ≈ ~300 decode
# tokens' worth (ADVICE r4: fixed 32-step checks can cost more than the
# dead tokens they save); the doubling schedule keeps the early checks
# (most exits are early) while bounding the sync tax on long tails at
# <1/256 steps. Worst-case overshoot past the EOS grows with the same
# schedule and stays ≤ the current check interval.
_EOS_CAP_MAX = 256


def _eos_capped_segments(segs: list) -> list:
    """Subdivide planner segments for EOS checking with doubling caps.
    Chunk sizes are planner quanta or powers of two between EOS_SEGMENT
    and ``_EOS_CAP_MAX`` — a bounded compiled-program set."""
    out = []
    cap = EOS_SEGMENT
    for n, w in segs:
        while n > 0:
            take = min(cap, n)
            out.append((take, w))
            n -= take
            cap = min(cap * 2, _EOS_CAP_MAX)
    return out


# graftscope program-key derivations — one per profiled entry point,
# reading the ACTUAL call operands in the exact model
# tools/graftcheck/recompile.py certifies (engine_call_keys), so the
# measured dispatch rings and the certified program populations join
# key-for-key (pinned by tests/test_graftscope.py).

def _prefill_scope_key(params, ids, pad):
    return (int(ids.shape[0]), int(ids.shape[1]), pad is not None)


def _prefill_chunked_scope_key(params, chunks, pad):
    return (int(chunks.shape[1]), int(chunks.shape[0]))


def _decode_seg_scope_key(params, token, cache, pad, step_keys, *,
                          sampling, window):
    return (int(token.shape[0]), int(step_keys.shape[0]), window, sampling,
            "per-row" if getattr(step_keys, "ndim", 2) == 3 else "one",
            pad is not None)


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Token-selection policy for one generate call.

    ``top_p`` (nucleus sampling, an extension beyond the reference's
    fixed top-k) further restricts the top-k survivors to the smallest
    prefix whose cumulative probability reaches ``top_p``; 1.0 disables
    it, reproducing the reference's math exactly.
    """

    mode: str = "greedy"  # "greedy" | "sample"
    temperature: float = REF_TEMPERATURE
    top_k: int = REF_TOP_K
    top_p: float = 1.0
    # Speculative-decoding routing flag: a spec-enabled policy batches
    # only with itself (SamplingConfig equality drives batch grouping,
    # so the existing FIFO policy-change handling applies unchanged) and
    # the batching front ends (runtime.batcher, runtime.iterbatch) route
    # such batches through the speculative engine. Pure routing
    # metadata: it never changes the sampler math — the spec engine
    # normalizes it away before compiling, so greedy stays token-exact
    # and sample keeps the same distribution.
    spec: bool = False

    def __post_init__(self):
        if self.mode not in ("greedy", "sample"):
            raise ValueError(f"unknown mode {self.mode!r}")
        if self.mode == "sample":
            if self.temperature <= 0:
                raise ValueError("temperature must be > 0 for sampling")
            if self.top_k < 1:
                raise ValueError("top_k must be >= 1")
            if not 0.0 < self.top_p <= 1.0:
                raise ValueError("top_p must be in (0, 1]")


def sampler_pmf(logits: jnp.ndarray, sampling: SamplingConfig,
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[..., vocab] logits -> ``(probs, idx)`` each ``[..., k]``: the
    sampler's distribution over the top-k survivors, descending.

    THE single definition of the sampling distribution — ``select_token``
    draws from it and speculative decoding's rejection sampler accepts
    against it, so the two paths cannot drift apart. Temperature + top-k
    mirror the reference (server.py:187-205); ``top_p`` then zeroes
    survivors outside the smallest prefix with cumulative mass >= top_p
    (the first survivor always stays) and renormalizes.
    """
    scaled = logits.astype(jnp.float32) / sampling.temperature
    top_vals, top_idx = jax.lax.top_k(scaled, sampling.top_k)
    probs = jax.nn.softmax(top_vals, axis=-1)          # descending
    if sampling.top_p < 1.0:
        cum_before = jnp.cumsum(probs, axis=-1) - probs
        keep = cum_before < sampling.top_p             # keeps index 0 always
        probs = jnp.where(keep, probs, 0.0)
        probs = probs / probs.sum(axis=-1, keepdims=True)
    return probs, top_idx


def select_token(logits: jnp.ndarray, sampling: SamplingConfig,
                 key: Optional[jax.Array]) -> jnp.ndarray:
    """[B, vocab] last-position logits -> [B] int32 next tokens, on device.

    Greedy is plain argmax. Sample mode draws from ``sampler_pmf`` — the
    reference's temperature/top-k math (server.py:187-205) plus optional
    nucleus filtering — as one fused device computation (categorical over
    the k survivors, mapped back through the top-k indices).

    ``key`` is either ONE key (a single joint draw over the batch — the
    single-stream form) or a ``[B, 2]`` stack of per-row keys (one
    independent draw per row, so a row's stream depends only on its own
    key — the basis of batched seeded sampling, ``runtime.batcher``).
    At B=1 the two forms draw identical bits (the categorical's gumbel
    bits depend on the element count, not the leading shape), so a solo
    run and a one-row per-row run are byte-equal — pinned in tests.
    """
    if sampling.mode == "greedy":
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    probs, top_idx = sampler_pmf(logits, sampling)
    if key.ndim == 2:                                  # [B, 2] per-row keys
        choice = jax.vmap(
            lambda k, p: jax.random.categorical(k, jnp.log(p)))(key, probs)
    else:
        choice = jax.random.categorical(key, jnp.log(probs), axis=-1)  # [B]
    return jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)


def _split_keys(key: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(prefill_key, decode_key) from either key form: a single key
    splits once; a ``[B, 2]`` per-row stack splits per row (each row's
    derivation identical to a solo run's — the byte-equality basis of
    batched seeded sampling)."""
    if key.ndim == 2:
        pair = jax.vmap(jax.random.split)(key)         # [B, 2, 2]
        return pair[:, 0], pair[:, 1]
    return tuple(jax.random.split(key))


def _step_keys(decode_key: jax.Array, n: int) -> jax.Array:
    """Per-decode-step keys: ``[n, 2]`` for a single key, ``[n, B, 2]``
    for a per-row stack (the scan consumes axis 0 either way). Splits are
    prefix-stable (``split(k, n)[i]`` is independent of ``n``), so a
    row's stream does not change when the batcher's steps bucket
    over-decodes past its own max_new_tokens."""
    if decode_key.ndim == 2:
        return jax.vmap(
            lambda k: jax.random.split(k, n))(decode_key).transpose(1, 0, 2)
    return jax.random.split(decode_key, n)


def left_pad(prompts, pad_id: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Ragged prompt list -> (ids [B, S_max] left-padded, pad [B]).

    Left-padding (not right-) is the TPU-shaped choice: every row's last
    prompt token lands in the same column, so prefill sampling reads one
    column, decode cache writes use one uniform ``dynamic_update_slice``
    offset for the whole batch, and no per-row scatter is ever needed. The
    pad prefix is excluded via per-row position offsets and the
    ``k_valid_from`` attention mask (ops.attention.causal_attention).
    """
    rows = [np.asarray(p, dtype=np.int32).reshape(-1) for p in prompts]
    if any(len(r) < 1 for r in rows):
        raise ValueError("every prompt must be non-empty")
    s_max = max(len(r) for r in rows)
    ids = np.full((len(rows), s_max), pad_id, dtype=np.int32)
    pad = np.zeros((len(rows),), dtype=np.int32)
    for i, r in enumerate(rows):
        ids[i, s_max - len(r):] = r
        pad[i] = s_max - len(r)
    return ids, pad


def prepare_generate(prompt_ids, max_new_tokens: int, max_seq: int,
                     sampling: SamplingConfig, key: Optional[jax.Array],
                     allow_ragged: bool = True,
                     pad: Optional[np.ndarray] = None,
                     ) -> Tuple[np.ndarray, int, int, jax.Array, np.ndarray]:
    """Shared validation/normalization for every ``generate`` front end
    (single-device engine and pipeline runner).

    Returns ``(ids [B,S], batch, prompt_len, key, pad [B])``. Ragged input
    (a list of unequal-length sequences) is left-padded; ``pad[b]`` is row
    b's pad-prefix length (all zeros for rectangular input). Callers that
    pre-pad themselves (``runtime.batcher`` buckets shapes) pass their own
    ``pad`` vector with rectangular ids. The overflow check is the static
    guard against silent KV-cache clamping: past ``max_seq``,
    ``dynamic_update_slice`` would clamp the write offset and corrupt
    generation without an error (see ops.attention.cached_attention).
    """
    if pad is not None:
        ids = np.asarray(prompt_ids)
        if ids.ndim != 2 or len(pad) != ids.shape[0]:
            raise ValueError("explicit pad requires [B, S] ids with one "
                             "pad entry per row")
        pad = np.asarray(pad, dtype=np.int32)
    elif (isinstance(prompt_ids, (list, tuple)) and prompt_ids
            and not np.isscalar(prompt_ids[0])
            and len({len(np.asarray(p).reshape(-1)) for p in prompt_ids}) > 1):
        if not allow_ragged:
            # Central guard: a ragged batch reaching a rectangular-only
            # front end would decode wrong tokens silently (one uniform
            # cache-write offset per batch), so refuse here, once.
            raise NotImplementedError(
                "this generate front end requires equal-length prompts; "
                "ragged batches go through runtime.engine.DecodeEngine")
        ids, pad = left_pad(prompt_ids)
    else:
        ids = np.asarray(prompt_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        pad = np.zeros((ids.shape[0],), dtype=np.int32)
    batch, prompt_len = ids.shape
    if prompt_len < 1:
        raise ValueError("prompt must be non-empty")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    total = prompt_len + max_new_tokens
    if total > max_seq:
        raise ValueError(
            f"prompt_len={prompt_len} + max_new_tokens={max_new_tokens} "
            f"= {total} exceeds max_seq={max_seq}; cache writes would "
            "silently clamp")
    if sampling.mode == "sample" and key is None:
        raise ValueError("sample mode requires an explicit PRNG key")
    if key is None:
        key = jax.random.PRNGKey(0)  # unused by greedy; fixed for shape
    elif getattr(key, "ndim", 1) == 2 and key.shape[0] != batch:
        raise ValueError(
            f"per-row key stack has {key.shape[0]} rows for a "
            f"batch of {batch}")
    return ids, batch, prompt_len, key, pad


@dataclasses.dataclass
class GenerateResult:
    """Tokens plus the timing the bench harness reports (BASELINE.md metric).

    ``decode_seconds`` times exactly ``decode_steps`` cached single-token
    forwards (= ``new_tokens - 1``: the first new token comes from the
    prefill logits, so its selection is inside the prefill window). The
    throughput/latency properties divide by ``decode_steps``, not
    ``new_tokens`` — dividing by ``new_tokens`` would overstate throughput
    by N/(N-1) and explode at N=1.
    """

    tokens: np.ndarray           # [B, prompt_len + new_tokens]
    prompt_len: int
    prefill_seconds: float
    decode_seconds: float
    new_tokens: int
    decode_steps: int
    pad: Optional[np.ndarray] = None  # [B] left-pad prefix lengths (ragged)
    # Speculative decode only (runtime.spec_decode): number of verify
    # forwards actually run; zero acceptance costs new_tokens - 1 verifies
    # (the first token comes from prefill), fewer means drafts landed.
    verify_steps: Optional[int] = None

    def row_tokens(self, i: int) -> np.ndarray:
        """Row i's tokens with its left-pad prefix stripped."""
        start = int(self.pad[i]) if self.pad is not None else 0
        return self.tokens[i, start:]

    @property
    def tokens_per_second(self) -> float:
        """Steady-state decode throughput (tokens/s across the batch)."""
        if self.decode_steps == 0:
            return float("nan")  # a 1-token generate has no decode window
        batch = self.tokens.shape[0]
        return self.decode_steps * batch / self.decode_seconds

    @property
    def per_token_latency(self) -> float:
        if self.decode_steps == 0:
            return float("nan")
        return self.decode_seconds / self.decode_steps


def _place_ep_params(params: Params, config, mesh, ep_axis: str) -> Params:
    """Expert-parallel placement: stacked expert leaves ``[L, E, ...]``
    shard over ``ep`` on their E axis (int8 ``QuantizedTensor`` codes and
    scales in lockstep), everything else replicates. Validates the
    mesh/family contract — see the ``DecodeEngine(mesh=...)`` docs."""
    if ep_axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {ep_axis!r} axis: {mesh.axis_names}")
    ep = mesh.shape[ep_axis]
    if config.n_experts % ep:
        raise ValueError(
            f"n_experts={config.n_experts} not divisible by ep={ep}")
    from jax.sharding import NamedSharding, PartitionSpec as P_

    def place(path, leaf):
        names = [getattr(p, "key", p) for p in path]
        if "experts" in names:
            ndim = leaf.q.ndim if hasattr(leaf, "q") else leaf.ndim
            spec = P_(None, ep_axis, *([None] * (ndim - 2)))
        else:
            spec = P_()
        return jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, P_(*spec[:x.ndim]))), leaf)

    return jax.tree_util.tree_map_with_path(
        place, params, is_leaf=lambda x: hasattr(x, "q") or hasattr(x, "ndim"))


def _place_tp_params(params: Params, config, mesh) -> Params:
    """Megatron tensor-parallel placement for dense-family decode: QKV/up
    projections column-sharded, attention-out/down row-sharded over the
    ``tp`` mesh axis (the family's ``parallel.spmd`` pspecs), embeddings
    and norms replicated. GSPMD derives the two per-block all-reduces;
    the KV cache shards over the head axis (``DecodeEngine._fresh_cache``)
    so each chip attends only its own heads. This is the one classic
    inference-parallelism axis the reference lacks entirely — its only
    split is between layers (reference server.py:63-64)."""
    from jax.sharding import NamedSharding

    from ..models.llama import LlamaConfig
    from ..parallel import spmd

    # the spmd pspec helpers key on the literal axis name "tp"
    if "tp" not in mesh.axis_names:
        raise ValueError(f"mesh has no 'tp' axis: {mesh.axis_names}")
    tp = mesh.shape["tp"]
    kv_heads = getattr(config, "n_kv_head", config.n_head)
    if config.n_head % tp or kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide n_head={config.n_head} and "
            f"n_kv_head={kv_heads}: the KV cache and attention shard "
            "over whole heads")
    specs = (spmd.llama_param_pspecs(mesh) if isinstance(config, LlamaConfig)
             else spmd.param_pspecs(mesh))

    def place(spec, leaf):
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, specs, params)


class DecodeEngine:
    """Single-model decode engine (pipeline-parallel variant in
    ``parallel.pipeline``): owns jitted prefill/decode programs keyed by
    static shapes, so repeated ``generate`` calls reuse compilations.

    ``boundaries`` switches on *staged* mode: params are partitioned into
    N validated pipeline stages (parallel.partition) and the compiled
    programs compose ``stage_apply`` over them — the whole multi-stage
    decode is still ONE program per phase (a single dispatch for the entire
    token scan), unlike the host-driven ``PipelineRunner`` which pays
    n_stages dispatches + transfers per token. On one chip this is the
    honest "N-shard" configuration (stage partitioning real, placement
    colocated); the multi-device single-program form lives in
    ``parallel.ppdecode`` (shard_map + ppermute over a pp mesh axis).
    """

    def __init__(self, params: Params, config: GPT2Config, max_seq: int,
                 dtype=jnp.float32, boundaries=None,
                 prefill_chunk: Optional[int] = None,
                 decode_kernel: str = "auto",
                 mesh=None, ep_axis: str = "ep"):
        """``dtype`` is the inference compute dtype: float params are cast
        once here and the KV cache allocates in it. bfloat16 halves weight
        and cache HBM traffic (the decode bottleneck — each token streams
        every weight once); LN statistics, softmax, and the final logits
        stay float32 (ops.layers.layer_norm, ops.attention, final_logits),
        so bf16 degrades only the matmul operand precision. float32 remains
        the greedy-parity mode BASELINE.json specifies.

        ``dtype="int8"`` selects weight-only int8: matmul kernels and the
        embedding/head table stored int8 with per-channel scales
        (ops.quant), activations and KV cache in bfloat16 — halves weight
        HBM traffic again over bf16. Tokens may diverge from the bf16
        stream within quantization error; fp32/bf16 remain the parity
        modes.

        ``prefill_chunk=C`` bounds the compile count under XLA's
        static-shape rule: a monolithic prefill compiles one program PER
        PROMPT LENGTH (a first-compile stall — tens of seconds on TPU —
        every time serving sees a new length), while chunked prefill
        left-pads the prompt to a multiple of ``C`` and scans one C-wide
        cached forward over the chunks, so the compiled-program space is
        the ~``max_seq/C`` distinct chunk COUNTS (each sharing the single
        scanned body) instead of every length. Numerically identical to
        monolithic prefill: the chunk padding rides the ragged-batch
        machinery (per-row position offsets + ``k_valid_from`` masking),
        token streams are byte-equal."""
        if max_seq > config.n_positions:
            raise ValueError(
                f"max_seq={max_seq} exceeds n_positions={config.n_positions}")
        from ..models import is_window_independent
        if prefill_chunk is not None:
            if prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk={prefill_chunk} must be >= 1")
            if not is_window_independent(config):
                # chunked prefill replays the prompt in C-token windows;
                # window-dependent routing (MoE) would route them
                # differently than the monolithic prefill, breaking the
                # byte-exactness contract. Refuse before any weight work.
                raise NotImplementedError(
                    "prefill_chunk requires window-independent routing; "
                    "MoE models prefill monolithically")
        # dtype is validated against the DECLARED engine regime
        # vocabulary (graftnum.REGIMES minus fp8 — that one is a
        # KV-block storage regime, kv_pool block_dtype) with a typed
        # error: an off-vocabulary dtype ("float16", a typo) used to
        # flow straight into astype and run a precision no
        # PRECISION_CONTRACT covers and no TOLERANCE_POLICY budgets.
        from ..utils.graftnum import engine_regime_of
        self.regime = engine_regime_of(dtype)
        quantize = self.regime == "int8"
        if quantize and mesh is not None and not hasattr(config, "n_experts"):
            # refuse BEFORE any weight work (quantizing a real checkpoint
            # takes seconds — same convention as the prefill_chunk guard)
            raise NotImplementedError(
                "int8 does not compose with tp decode: the int8 "
                "streaming matmuls are unpartitioned Pallas kernels "
                "GSPMD cannot split; tp decode runs fp32/bf16")
        if quantize:
            dtype = jnp.bfloat16  # activation/KV-cache dtype under int8
            from ..ops.quant import quantize_params
            # quantize straight from the checkpoint dtype: a bf16 pre-cast
            # would truncate mantissas BEFORE rounding to int8 codes
            # (double rounding), wasting quantization accuracy for nothing
            self.params = quantize_params(params, dtype)
        else:
            self.params = jax.tree.map(
                lambda x: x.astype(dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        self.config = config
        self.max_seq = max_seq
        self.dtype = dtype
        # Mesh decode — the family picks the parallelism axis:
        #
        # - MoE + mesh("ep"): expert-parallel inference. Stacked expert
        #   kernels/biases shard over their E axis and everything else
        #   replicates — each chip holds (and streams) E/ep experts'
        #   weights, and GSPMD derives the dispatch/combine collectives
        #   from the dense formulation (the routed-gather fast path is
        #   disabled under a mesh: a jnp.take over the sharded E axis
        #   would make XLA all-gather the full expert stack, exactly the
        #   traffic ep-sharding exists to avoid).
        # - dense (GPT-2 / llama) + mesh("tp"): tensor-parallel decode.
        #   Megatron column/row-sharded projections (_place_tp_params),
        #   KV cache sharded over heads, GSPMD-derived per-block
        #   all-reduces — single-stream latency scaling across chips.
        self._mesh = mesh
        self._mesh_mode: Optional[str] = None
        if mesh is not None:
            if boundaries is not None:
                raise ValueError("mesh decode (ep/tp) and stage "
                                 "partitioning are mutually exclusive")
            if hasattr(config, "n_experts"):
                self._mesh_mode = "ep"
                self.params = _place_ep_params(self.params, config, mesh,
                                               ep_axis)
            else:
                # (int8 x tp already refused above, before weight work)
                self._mesh_mode = "tp"
                self.params = _place_tp_params(self.params, config, mesh)
        # Model dispatch: any family module exposing the
        # (forward_with_cache, make_cache) pair can be decoded
        # (models.family_module — gpt2, moe, llama). Stage partitioning
        # covers the dense families (GPT-2 and llama — parallel.partition
        # dispatches structurally); MoE's expert tree decodes unstaged.
        from ..models import family_module, is_stage_partitionable
        self._model = family_module(config)
        if boundaries is not None and not is_stage_partitionable(config):
            raise NotImplementedError(
                "pipeline stage partitioning (boundaries) covers the "
                f"dense GPT-2 and llama param trees; "
                f"{type(config).__name__} models decode unstaged")
        if boundaries is None:
            self.specs = None
            self.stage_params = None
        else:
            from ..parallel import partition as P
            self.specs = P.make_stage_specs(config.n_layer, boundaries)
            self.stage_params = P.partition_params(self.params, self.specs)
            # The compiled programs only ever see the staged copy; dropping
            # the monolithic pytree keeps one set of weights resident, not
            # two (the slices are new buffers).
            self.params = None
        # the weight tree is now FINAL (quantized/placed/staged) — this
        # is the copy the compiled programs read, so it is the copy the
        # HBM ledger attributes (graftmem measures live buffer nbytes,
        # so a quantized tree registers its quantized footprint)
        graftmem.track(self, "params", "params",
                       self.params if self.params is not None
                       else self.stage_params)
        self.prefill_chunk = prefill_chunk
        # Decode-attention dispatch (``decode_kernel``): "auto" routes
        # single-token decode steps through the Pallas flash-decode kernel
        # on TPU (in-place cache write + depth-adaptive block reads —
        # ops.decode_attention has the measurements), "xla" keeps the
        # einsum path (the byte-pinned parity mode), "interpret" forces
        # the kernel in interpret mode for CPU tests. The kernel needs the
        # cache allocated in whole blocks, so the PHYSICAL cache rounds up
        # to a BLOCK_S multiple (capped at n_positions). On ineligible
        # shapes "auto" falls back to "xla" with the exact ``max_seq``
        # allocation; an EXPLICIT "interpret" request refuses instead
        # (see the raise below).
        from ..ops import decode_attention as _DA
        _KERNEL_MODES = ("auto", "xla", "interpret", "layer",
                         "layer-interpret", "mega", "mega-interpret")
        if decode_kernel not in _KERNEL_MODES:
            raise ValueError(
                f"decode_kernel={decode_kernel!r} not one of {_KERNEL_MODES}"
                " ('auto'/'interpret' pick the best kernel; 'layer*' and "
                "'mega*' force the per-layer / whole-stack kernel)")
        self._cache_seq = max_seq
        self._decode_kernel: Optional[str] = None
        # "auto" engages only for non-fp32 dtypes (fp32 is BASELINE.json's
        # byte-pinned greedy-parity mode; the kernel's online softmax is
        # allclose-not-bitwise vs the einsum path) and only without an ep
        # mesh (the kernel's manual DMAs don't compose with GSPMD
        # partitioning — "auto" quietly resolves to XLA there, while the
        # EXPLICIT kernel request refuses rather than silently running
        # something else).
        explicit_interp = decode_kernel in ("interpret", "layer-interpret",
                                            "mega-interpret")
        explicit_kernel = decode_kernel not in ("auto", "xla")
        if mesh is not None and explicit_kernel:
            raise ValueError(
                f"decode_kernel={decode_kernel!r} does not compose with a "
                "mesh (the Pallas decode kernels are unpartitioned); use "
                "'auto' or 'xla'")
        want = mesh is None and (
            explicit_kernel
            or (decode_kernel == "auto"
                and jax.default_backend() == "tpu"
                and dtype != jnp.float32))
        if want:
            rounded = min(-(-max_seq // _DA.BLOCK_S) * _DA.BLOCK_S,
                          config.n_positions)
            base_ok = _DA.eligible(rounded, config.head_dim, 1)
            # whole-stack megakernel (ops.decode_layer): one launch per
            # decode step instead of one per op — plain (unstaged)
            # GPT-2/llama engines with lane-aligned dims inside the VMEM
            # budget. The model falls back to the per-layer kernel at
            # trace time for batches past MAX_BATCH.
            from ..models import gpt2 as _g
            from ..models import llama as _ll
            from ..ops import decode_layer as _DL
            # staged engines compose: each stage's stacked blocks run as
            # their own whole-stack launch (parallel.partition.
            # stage_apply's mega route) — n_stages launches per step
            # instead of one per op
            isize = jnp.dtype(dtype).itemsize
            mega_ok = base_ok and (
                (self._model is _g and _DL.eligible(config, rounded, isize))
                or (self._model is _ll
                    and _DL.llama_eligible(config, rounded, isize)))
            if decode_kernel in ("mega", "mega-interpret") and not mega_ok:
                raise ValueError(
                    f"decode_kernel={decode_kernel!r} requested but the "
                    "megakernel is ineligible here (needs a GPT-2/llama "
                    "engine with lane-aligned dims within the VMEM "
                    "budget and a whole-block cache). Note: even an "
                    "eligible mega engine falls back to the per-layer "
                    f"kernel at trace time past {_DL.MAX_BATCH} batch "
                    "rows (its VMEM batch budget)")
            if base_ok:
                self._cache_seq = rounded
                use_mega = (mega_ok and decode_kernel
                            not in ("layer", "layer-interpret"))
                if use_mega:
                    self._decode_kernel = ("mega-interpret"
                                           if explicit_interp else "mega")
                else:
                    self._decode_kernel = ("interpret" if explicit_interp
                                           else "device")
            elif explicit_kernel:
                # An EXPLICIT kernel request must never silently run
                # something else (mirrors the mesh refusal above): a
                # config slip would otherwise stop exercising the kernel
                # in tests that forget to assert _decode_kernel. Only
                # "auto" may quietly resolve to XLA.
                raise ValueError(
                    f"decode_kernel={decode_kernel!r} requested but the "
                    f"geometry is ineligible (head_dim={config.head_dim}, "
                    f"cache={rounded}): needs 2*head_dim % 128 == 0 and a "
                    f"whole-{_DA.BLOCK_S}-block cache; use 'auto' or 'xla'")
        # Prefill allocates its cache *inside* the program (zeros are free
        # under XLA and the layout matches the decode program exactly);
        # decode donates the prefill-produced cache so the two
        # [L, B, H, max_seq, hd] buffers update in place instead of
        # doubling.
        # each jit site rides a graftscope dispatch timer (PROFILED_SCOPES
        # contract): per-call wall clock into the bounded attribution
        # ring, keyed by the certifier's program-key model
        self._prefill = graftscope.instrument(
            jax.jit(self._prefill_impl), "engine._prefill",
            key_fn=_prefill_scope_key)
        self._prefill_chunked = graftscope.instrument(
            jax.jit(self._prefill_chunked_impl), "engine._prefill_chunked",
            key_fn=_prefill_chunked_scope_key)
        # static args: the sampling policy and the attention window (both
        # change the traced program; the step count rides the step_keys
        # shape).
        self._decode_seg = graftscope.instrument(
            jax.jit(self._decode_seg_impl, donate_argnums=(2,),
                    static_argnames=("sampling", "window")),
            "engine._decode_seg", key_fn=_decode_seg_scope_key)
        # compile-event accounting (utils.metrics.CompileWatch): every NEW
        # program entering these caches increments compile_events_total
        # with a phase label — checked after invocations, off the hot
        # device path, so compile storms are observable as counter bursts.
        self._compile_watches = (CompileWatch("prefill", self._prefill),
                                 CompileWatch("prefill",
                                              self._prefill_chunked),
                                 CompileWatch("decode", self._decode_seg))

    def _note_compiles(self) -> None:
        """Diff the jitted program caches into ``compile_events_total``
        and refresh the program-count gauge. Called after generate phases
        (and by the iteration scheduler after its segment dispatches)."""
        for w in self._compile_watches:
            w.check()
        # w.seen() (locked read): CompileWatch._seen is declared guarded
        # state, and solo engines are driven straight from concurrent
        # server handler threads
        REGISTRY.gauge("jit_program_cache_size",
                       sum(w.seen() for w in self._compile_watches),
                       component="engine")

    # -- compiled programs ---------------------------------------------------

    def _fresh_cache(self, batch: int):
        # allocation size may exceed the semantic ``max_seq`` bound: the
        # decode kernel wants whole BLOCK_S blocks (see __init__). Kernel
        # mode allocates the FUSED layout (K|V interleaved rows — see
        # ops.attention.create_fused_cache) the kernel's aligned DMAs
        # require; the XLA mode keeps the family's separate buffers.
        heads = getattr(self.config, "n_kv_head", self.config.n_head)
        if self._decode_kernel is not None:
            from ..ops.attention import create_fused_cache
            if self.specs is None:
                return create_fused_cache(self.config.n_layer, batch, heads,
                                          self._cache_seq,
                                          self.config.head_dim, self.dtype)
            return [create_fused_cache(s.n_blocks, batch, heads,
                                       self._cache_seq, self.config.head_dim,
                                       self.dtype) for s in self.specs]
        if self.specs is None:
            cache = self._model.make_cache(self.config, batch,
                                           self._cache_seq, self.dtype)
            if self._mesh_mode == "tp":
                # [L, B, H, S, hd] buffers shard over the HEAD axis: each
                # chip's attention reads/writes only its own heads' cache
                # slots — no cross-chip KV traffic, only the two
                # GSPMD-inserted per-block all-reduces touch ICI
                from jax.sharding import NamedSharding, PartitionSpec as P_
                sh = NamedSharding(self._mesh, P_(None, None, "tp"))
                cache = KVCache(
                    k=jax.lax.with_sharding_constraint(cache.k, sh),
                    v=jax.lax.with_sharding_constraint(cache.v, sh),
                    length=cache.length)
            return cache
        from ..parallel import partition as P
        return [P.make_stage_cache(s, self.config, batch, self._cache_seq,
                                   self.dtype) for s in self.specs]

    def _forward_cached(self, params, x, cache, pad, flash_prefill=False):
        """One cached forward — plain (fused model) or staged composition.

        ``flash_prefill`` is the static fresh-cache-prefill flag (see
        ``_prefill_impl``); the staged path ignores it (stage prefills
        are short at current scales). Single-token calls route through
        the flash-decode kernel when enabled (``decode_kernel``); the
        model gates on query length, so prefill and the speculative
        multi-token verify forwards stay on the XLA path.
        """
        if self.specs is None:
            kw = {}
            if self._mesh_mode == "ep":
                kw["routed_mlp"] = False  # MoE only (validated in __init__)
            return self._model.forward_with_cache(
                params, x, self.config, cache, pad,
                flash_prefill=flash_prefill,
                decode_kernel=self._decode_kernel, **kw)
        from ..parallel import partition as P
        new_caches = []
        for sp, spec, c in zip(params, self.specs, cache):
            x, c = P.stage_apply(sp, spec, self.config, x, c, pad,
                                 decode_kernel=self._decode_kernel)
            new_caches.append(c)
        return x, new_caches

    def _run_params(self):
        return self.stage_params if self.specs is not None else self.params

    def _prefill_impl(self, params: Params, ids: jnp.ndarray,
                      pad: Optional[jnp.ndarray],
                      ) -> Tuple[jnp.ndarray, KVCache]:
        cache = self._fresh_cache(ids.shape[0])
        # Fresh-cache prefill at offset 0 with no pad mask is plain causal
        # attention — route it through the Pallas flash kernel when the
        # config asks for it (attention_impl="pallas"): no O(S^2) score
        # materialization at long context. All conditions are static at
        # trace time; flash_eligible keeps ragged user lengths the kernel
        # cannot tile (it would fall back to one full-S VMEM block) on
        # the XLA path.
        from ..ops.flash_attention import flash_eligible, flash_profitable
        # _mesh gate: the Mosaic flash kernel is unpartitioned — under a
        # tp/ep mesh GSPMD cannot split it, so mesh decode keeps the XLA
        # prefill (same rule as the decode kernel and int8 matmuls)
        flash = (self.config.attention_impl == "pallas" and pad is None
                 and ids.shape[1] > 1 and self.specs is None
                 and self._mesh is None
                 and flash_eligible(ids.shape[1])
                 and flash_profitable(ids.shape[1]))
        logits, cache = self._forward_cached(params, ids, cache, pad,
                                             flash_prefill=flash)
        return logits[:, -1], cache

    def _prefill_chunked_impl(self, params: Params, chunks: jnp.ndarray,
                              pad: jnp.ndarray,
                              ) -> Tuple[jnp.ndarray, KVCache]:
        """``chunks`` [n, B, C] (left-pad-aligned); ``pad`` [B] includes
        the alignment pad. One C-wide cached forward scanned over the
        chunk axis — the compiled body is shared by every chunk, so the
        program space is per chunk COUNT, not per prompt length."""
        cache = self._fresh_cache(chunks.shape[1])

        def body(cache, chunk):
            logits, cache = self._forward_cached(params, chunk, cache, pad)
            return cache, logits[:, -1]

        cache, last = jax.lax.scan(body, cache, chunks)
        return last[-1], cache

    def _align_chunks(self, ids: np.ndarray, pad: np.ndarray,
                      prompt_len: int, reserve: int):
        """Left-pad ``ids`` to a multiple of ``prefill_chunk`` when chunked
        prefill applies. Returns ``(ids, pad, prompt_len, chunk_or_None)``;
        ``chunk=None`` means use the monolithic prefill (chunking off,
        prompt fits in one chunk, or no cache headroom for the alignment
        pad given ``reserve`` upcoming tokens). Correctness never depends
        on which path is taken."""
        chunk = self.prefill_chunk
        if not chunk or prompt_len <= chunk:
            return ids, pad, prompt_len, None
        n_chunks = -(-prompt_len // chunk)
        if n_chunks * chunk + reserve > self.max_seq:
            return ids, pad, prompt_len, None
        extra = n_chunks * chunk - prompt_len
        if extra:
            ids = np.concatenate(
                [np.zeros((ids.shape[0], extra), np.int32), ids], axis=1)
            pad = pad + extra
        return ids, pad, n_chunks * chunk, chunk

    # -- windowed decode segments --------------------------------------------
    #
    # The decode scan's attention reads the whole [*, max_seq, *] cache
    # every step even when only `depth` slots are valid: a 528-slot cache
    # decoded from depth 16 streams 33x the useful KV bytes on step one.
    # Splitting the scan into segments with STATIC, growing windows (the
    # next power-of-two bucket over the segment's deepest slot) keeps
    # every shape static under jit while the attention read tracks actual
    # depth. Byte-exact: slots >= depth are masked out either way, and the
    # per-step PRNG keys are split once for the whole decode, so sampled
    # streams are identical to the unsegmented program's.

    def _slice_cache(self, cache, window: int):
        def cut(c: KVCache) -> KVCache:
            return KVCache(k=c.k[..., :window, :], v=c.v[..., :window, :],
                           length=c.length)
        return [cut(c) for c in cache] if isinstance(cache, list) else cut(cache)

    def _merge_window(self, full, sub):
        def merge(f: KVCache, s: KVCache) -> KVCache:
            zeros = (0,) * f.k.ndim
            return KVCache(k=jax.lax.dynamic_update_slice(f.k, s.k, zeros),
                           v=jax.lax.dynamic_update_slice(f.v, s.v, zeros),
                           length=s.length)
        if isinstance(full, list):
            return [merge(f, s) for f, s in zip(full, sub)]
        return merge(full, sub)

    # windowed-decode bucket policy, shared with runtime.iterbatch
    WINDOW_BUCKET = 128

    def _decode_window(self, deepest: int) -> Optional[int]:
        """The attention window for a segment whose deepest cache slot is
        ``deepest``: the smallest power-of-two multiple of
        ``WINDOW_BUCKET`` covering it, or ``None`` for the full-cache
        program (window would reach ``max_seq``, or the flash-decode
        kernel is active — its block loop already depth-bounds reads).
        THE single definition of the bucket policy; ``_segments`` and the
        iteration-level scheduler both derive windows from it."""
        if self._decode_kernel is not None:
            return None
        w = self.WINDOW_BUCKET
        while w < deepest:
            w *= 2
        return None if w >= self.max_seq else w

    def _segments(self, start_depth: int, steps: int,
                  bucket: Optional[int] = None, quant: int = 32) -> list:
        """Split ``steps - 1`` decode forwards into ``(n_forwards, window)``
        segments. The forward at cache depth ``d`` needs ``window >= d+1``;
        windows are power-of-two multiples of ``bucket``. Once the window
        reaches ``max_seq`` the remainder runs as ``(n, None)`` — the plain
        full-cache program, shared by every generate (no slice/merge).

        Compile-space note: intermediate segment lengths are quantized
        DOWN to multiples of ``quant`` (a depth within ``quant`` of a
        window edge skips straight to the next window), so the program
        set is bounded by {multiples of quant} x {log windows} no matter
        how many distinct prompt depths serving sees — unbatched traffic
        with arbitrary prompt lengths compiles the same handful of
        bodies. Only the FINAL segment's length is request-keyed
        (= remaining steps), exactly like the pre-windowing steps-keyed
        scheme, and the batcher's ``steps_bucket`` already quantizes that.

        With the flash-decode kernel active, segmentation is pointless:
        the kernel's block loop already bounds its reads by the live
        depth (a dynamic trip count — no recompiles), so the whole decode
        runs as one full-cache program."""
        if self._decode_kernel is not None:
            return [(steps - 1, None)]
        bucket = bucket or self.WINDOW_BUCKET
        total = steps - 1
        segs = []
        d = start_depth
        while total > 0:
            w = bucket
            while w < d + 1:
                w *= 2
            if w - d < quant and w < self.max_seq:
                w *= 2  # too close to the edge: a sub-quant segment
                        # would mint a new program for little read saving
            if w >= self.max_seq:
                segs.append((total, None))
                break
            room = w - d
            if room >= total:
                segs.append((total, w))
                break
            n = (room // quant) * quant
            segs.append((n, w))
            d += n
            total -= n
        return segs

    def _decode_seg_impl(self, params: Params, token: jnp.ndarray,
                         cache, pad: Optional[jnp.ndarray],
                         step_keys: jax.Array, *,
                         sampling: SamplingConfig,
                         window: Optional[int]):
        """Forward ``len(step_keys)`` cached single-token steps from
        ``token``; attention reads only the first ``window`` cache slots
        (sliced out statically; the updated slice merges back into the
        donated full buffer on exit). Returns ``(tokens [B, n], cache)``."""
        sub = self._slice_cache(cache, window) if window else cache

        def body(carry, step_key):
            token, c = carry
            logits, c = self._forward_cached(params, token[:, None], c, pad)
            nxt = select_token(logits[:, -1], sampling, step_key)
            return (nxt, c), nxt

        (_, sub), out = jax.lax.scan(body, (token, sub), step_keys)
        cache = self._merge_window(cache, sub) if window else sub
        return out.T, cache  # [n, B] -> [B, n]

    # -- public API ----------------------------------------------------------

    def generate(self, prompt_ids, max_new_tokens: int,
                 sampling: SamplingConfig = SamplingConfig(),
                 key: Optional[jax.Array] = None,
                 pad: Optional[np.ndarray] = None,
                 eos_id: Optional[int] = None) -> GenerateResult:
        """[B, S] (or [S]) prompt ids -> GenerateResult with [B, S+N] tokens.

        Validation (including the static cache-overflow guard) is shared
        with the pipeline runner via ``prepare_generate``. ``pad`` lets
        pre-padded callers (runtime.batcher) declare their left-pad
        prefixes explicitly.

        ``eos_id`` arms on-device-work early exit: the decode runs in
        chunks with DOUBLING caps (``EOS_SEGMENT`` = 32, then 64, 128,
        up to ``_EOS_CAP_MAX`` = 256 steps) and stops at the first
        boundary where EVERY row has emitted ``eos_id`` — the emitted
        tokens are the byte-exact prefix of the uncapped stream (same
        programs, same prefix-stable per-step keys), but dead tokens
        past the last row's EOS stop costing device time. Each armed
        chunk costs one host sync (the unarmed path keeps its zero-sync
        dispatch pipeline); the doubling schedule bounds that tax on
        long generations while keeping early exits fine-grained —
        worst-case overshoot past the EOS equals the current chunk size
        (up to 256 steps late in a long decode). Serving arms it only
        for ``stop_at_eos`` requests. May return fewer than
        ``max_new_tokens`` tokens (``GenerateResult.new_tokens``).
        """
        ids, batch, prompt_len, key, pad = prepare_generate(
            prompt_ids, max_new_tokens, self.max_seq, sampling, key, pad=pad)

        ids, pad, prompt_len, chunk = self._align_chunks(
            ids, pad, prompt_len, reserve=max_new_tokens)

        ids_j = jnp.asarray(ids, dtype=jnp.int32)
        # Rectangular batches keep pad=None: the compiled programs then skip
        # the per-row mask entirely (same numerics, no [B,Sq,Skv] mask
        # materialization) and stay byte-identical to the pre-ragged path.
        pad_j = jnp.asarray(pad) if pad.any() else None

        t0 = time.perf_counter()
        prefill_key, decode_key = _split_keys(key)
        run_params = self._run_params()
        if chunk:
            n_chunks = ids_j.shape[1] // chunk
            chunks = ids_j.reshape(batch, n_chunks, chunk).transpose(1, 0, 2)
            last_logits, cache = self._prefill_chunked(
                run_params, chunks,
                pad_j if pad_j is not None
                else jnp.zeros((batch,), jnp.int32))
        else:
            last_logits, cache = self._prefill(run_params, ids_j, pad_j)
        first = select_token(last_logits, sampling, prefill_key)
        first.block_until_ready()
        t1 = time.perf_counter()
        tracing.record("prefill", t0, t1, batch=batch,
                       prompt_len=prompt_len, chunked=bool(chunk))
        # KV reservation in the pool's block denomination (see
        # utils.metrics.kv_block_gauges): the contiguous arena this
        # generate holds, vs its allocated capacity
        kv_block_gauges("engine", batch * (prompt_len + max_new_tokens),
                        batch * self._cache_seq)
        return self._decode_and_pack(run_params, ids, pad, pad_j, first,
                                     cache, decode_key, max_new_tokens,
                                     sampling, prompt_len, t1 - t0,
                                     eos_id=eos_id)

    def _decode_and_pack(self, run_params, ids, pad, pad_j, first, cache,
                         decode_key, max_new_tokens: int,
                         sampling: SamplingConfig, prompt_len: int,
                         prefill_seconds: float,
                         eos_id: Optional[int] = None) -> GenerateResult:
        """Run the compiled decode scan off a prepared (first token, cache)
        state and assemble the GenerateResult — shared by ``generate`` and
        the prefix-cache front end (runtime.prefix_cache), which prepares
        the prefill state its own way. Donates ``cache``.

        The decode runs as windowed segments (see ``_segments``): each
        segment is one compiled scan whose attention reads only the
        current power-of-two depth bucket of the cache, so shallow steps
        stop paying for the full ``max_seq`` read. Exact, and the same
        program count as before for short generations.

        ``eos_id`` (see ``generate``) subdivides segments with DOUBLING
        caps (32, 64, ... ``_EOS_CAP_MAX``) and fetches each chunk's
        tokens; the loop exits at the first boundary where every row has
        emitted the id. Early exits keep their fine granularity while a
        long armed tail pays logarithmically few syncs (ADVICE r4: on
        high-RTT tunnels fixed 32-step checks can cost more than the
        dead tokens they save). Program set stays bounded: chunk sizes
        are powers of two or planner quanta."""
        t1 = time.perf_counter()
        # working-view ledger entry: the contiguous cache is live for
        # exactly this generation (handle-keyed — concurrent generates
        # each hold their own entry); released at the ``del`` below.
        # Segment rebinds are donated and shape-identical, so one
        # registration covers the whole decode.
        mem_h = graftmem.track(self, "cache", "engine_cache", cache)
        steps = max_new_tokens
        parts = [first[:, None]]
        token = first
        segs = self._segments(prompt_len, steps)
        done = None
        if eos_id is not None:
            segs = _eos_capped_segments(segs)
            done = np.asarray(first) == eos_id
        if steps > 1 and not (done is not None and done.all()):
            step_keys = _step_keys(decode_key, steps - 1)
            used = 0
            for n, window in segs:
                out, cache = self._decode_seg(
                    run_params, token, cache, pad_j,
                    step_keys[used:used + n], sampling=sampling,
                    window=window)
                token = out[:, -1]
                parts.append(out)
                used += n
                if done is not None:
                    done |= (np.asarray(out) == eos_id).any(axis=1)
                    if done.all():
                        break
        del cache  # last segment's output aliases the donated prefill cache
        graftmem.release(mem_h)
        new = np.asarray(jax.block_until_ready(jnp.concatenate(parts, axis=1)))
        t2 = time.perf_counter()
        steps_run = new.shape[1] - 1
        tracing.record("decode", t1, t2, batch=new.shape[0],
                       steps=new.shape[1], segments=len(segs),
                       step_ms=round((t2 - t1) / max(steps_run, 1) * 1e3, 3))
        if steps_run > 0:
            # per-decode-step time, DEVICE-inclusive: this window closes
            # after the block_until_ready fetch above, so it covers real
            # execution — unlike the scheduler-side dispatch windows
            # (see utils.metrics METRIC_CATALOG's truth note)
            REGISTRY.observe("decode_step_seconds", (t2 - t1) / steps_run,
                             component="engine")
        self._note_compiles()
        # generation done: its cache reservation is released (an idle
        # server must not keep reporting the last request's blocks)
        kv_block_gauges("engine", 0, new.shape[0] * self._cache_seq)

        tokens = np.concatenate([ids, new], axis=1)
        return GenerateResult(tokens=tokens, prompt_len=prompt_len,
                              prefill_seconds=prefill_seconds,
                              decode_seconds=t2 - t1,
                              new_tokens=new.shape[1],
                              decode_steps=new.shape[1] - 1,
                              pad=pad if pad.any() else None)
