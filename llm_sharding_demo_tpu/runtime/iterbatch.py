"""Iteration-level continuous batching: join/retire at segment boundaries.

``runtime.batcher`` batches at ADMISSION: it groups waiting requests,
runs one bucketed decode to completion, and only then looks at the queue
again — a request arriving mid-decode waits out the whole batch
(VERDICT r3 weak #3). This module schedules at ITERATION level, the
vLLM-style upgrade: the decode runs as fixed-size compiled segments, and
between segments the scheduler

- **admits** queued requests into free batch slots (solo bucketed
  prefill, then the row's K/V merges into the live cache at the current
  depth — the same roll-and-mask move the prefix batcher uses), and
- **retires** rows that finished (their ``max_new_tokens`` reached, or
  their ``eos_id`` emitted — early-EOS rows free their slot instead of
  decoding dead tokens to the end of the batch).

The segment loop dispatches asynchronously: segments queue back-to-back
on the device with NO host sync unless a decision is needed (a retiring
row's tokens are fetched for delivery; EOS-armed rows force a fetch per
segment). The device never idles waiting for the host on the fast path.

Exactness is the same bar as the admission batcher, per row:

- greedy rows equal their solo engine runs token-for-token (row-
  independent attention + left-pad masking — a joined row's cache
  content at slots ``[d - plen, d)`` with ``pad = d - plen`` is exactly
  a solo run's, shifted);
- seeded sample rows are byte-equal to solo runs: per-row keys with the
  row's OWN step offsets (``split(dk, n)[t]`` is prefix-stable, so a
  row joining at depth d still consumes key ``t`` at its step ``t``).

Batches are policy-pure (one SamplingConfig per live batch, like the
admission batcher); an incompatible arrival closes admission and seeds
the next batch, preserving FIFO. MoE is refused: its routing is not
window-independent (``models.is_window_independent``), so a row's
tokens could depend on batch composition.

Batches are RIGHT-SIZED (ADVICE r4): a batch compiles at the smallest
power-of-two width that fits its seed and grows on demand when an
arrival finds no free slot — a lone request decodes at width 1 instead
of paying ``max_batch`` x ghost-row FLOPs. Ghost rows (width minus live
rows) replicate a real row; per-row independence keeps them inert.

Compiled-program inventory (bounded): the engine's prefill programs
(prompt-bucketed), ONE decode-segment program per (window bucket,
sampling, power-of-two batch width up to ``max_batch``) and segment
length (plus cache-tail remainders, quantized by construction), one
admit program per width, and one tiny grow program per adjacent width
pair.

Speculative segments (``spec=``): a batch whose policy carries the
``SamplingConfig.spec`` flag advances through the speculative engine's
draft-verify SEGMENT program (runtime.spec_decode.``_seg_b``) instead of
the single-token segment scan: each segment runs up to
``seg_steps // (draft_len + 1)`` verify forwards, every row accepting
its own ``k_i in [0, draft_len]`` drafts per verify with a per-row
cache rewind (uniform-depth re-sync — rows stay mergeable, so admission
and retirement keep working mid-speculation). Per-row emission within a
segment is ragged, so a spec segment costs ONE host sync (fetching
per-row counts + the new depth) — the price of data-dependent progress,
same class as EOS-armed batches. Exactness bar unchanged: every row —
seeded sample rows included — is byte-equal to its solo
``SpecDecodeEngine.generate`` run (per-row key chains resume across
segments; joiners start their chain at their own step 0). Spec batches
admit only rows speculation is exact for (prompt >= ngram, draft_len
slots of headroom); the ``spec`` flag is part of policy equality, so a
spec arrival during a plain batch (or vice versa) closes admission and
seeds the next batch — the same FIFO-preserving policy-change handling
as any sampling change. One spec-segment program per (width, policy):
acceptance counts are traced, never program keys.

Prefix-cache composition (``prefix=``): admissions prefill through the
prefix store (``PrefixCachingEngine.prefill_state``) — a joiner whose
prompt shares a cached prefix forwards only its suffix before merging
into the live batch at the current depth. Exact (store replay is
byte-identical to a cold prefill) and compile-bounded by the store's
chunk programs.

Paged KV composition (``pool=``, runtime.kv_pool): rows' KV state lives
in ref-counted pool BLOCKS between segments instead of a permanently
allocated ``[B, max_seq]`` arena. Each segment boundary gathers the
tabled rows into a contiguous working cache, runs the UNCHANGED segment
program (same program keys, byte-identical tokens), and scatters the
updated rows back; fully-padded table positions point at the shared
trash block, so a short row costs ``ceil(content/block_size)`` blocks,
not ``max_seq`` slots. The pool is also the ADMISSION authority:

- admission of a policy-compatible request defers (without closing the
  batch) while the allocator's watermark says its blocks don't fit —
  and ``serving.app`` turns sustained refusal into 429 + Retry-After;
- when live rows GROW past a block boundary and allocation fails even
  after LRU-evicting prefix entries, the scheduler PREEMPTS the
  lowest-priority row (latest admission order): fetch its emitted
  tokens, free its blocks, park it. Parked rows resume — oldest first,
  before any queued request — by RECOMPUTE: re-prefill prompt +
  already-emitted tokens (one bucketed solo prefill, exactly the
  admission move) and continue the row's own per-step PRNG chain.
  Byte-identical to the un-preempted stream (prefix-stable key splits;
  prefill-recomputed KV equals incrementally-decoded KV — pinned by
  tests for greedy and seeded sample, plain and spec batches).

Every admission/watermark/preemption quantity above is denominated in
BLOCKS (``allocator.blocks_for``), never bytes — so a quantized pool
(``block_dtype`` set: narrow storage, smaller bytes-per-block) raises
the admissible row count at a fixed HBM budget purely by being built
with more blocks, with zero scheduler branches. Under quantized storage
the resume-by-recompute stream is equivalent within the declared
``kv.int8``/``kv.fp8`` tolerance budgets rather than byte-identical
(rescattering recomputes content scales — see runtime.kv_pool); the
full-precision pool keeps every byte-equality pin above.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.attention import KVCache
from ..utils import graftfault, graftmem, graftsched, graftscope, \
    grafttime, tracing
from ..utils.metrics import REGISTRY, kv_block_gauges
from .batcher import _round_up
from .engine import (DecodeEngine, GenerateResult, SamplingConfig,
                     select_token)


# Static-analysis contract (tools/graftcheck): every ``jax.jit`` site in
# this module, by holding name — enumerated by the recompile-budget
# certifier; an undeclared site is a lint finding.
JIT_ENTRY_POINTS = ("_admit_cache",)

# Observability contract (tools/graftcheck scope pass + utils/graftscope):
# the admission-merge program's dispatches are timed into the graftscope
# ring (graftscope.instrument at the jit site below).
PROFILED_SCOPES = ("_admit_cache",)

# Donation contract (tools/graftcheck sanitize pass): ``_admit_cache``
# consumes the live batch cache (arg 0) — callers re-bind
# ``state.cache`` from its output, never the donated input.
DONATED_ARGS = {"_admit_cache": (0,)}

# Pool-mover lease scopes (tools/graftcheck sanitize pass): the only
# functions allowed to invoke pool gather/scatter movers — each holds a
# live BlockAllocator lease on every block id it moves (table entries
# are this batch's ``_Slot.blk_ids`` allocations or the trash block).
POOL_MOVER_SCOPES = ("IterBatchingEngine._init_tables",
                     "IterBatchingEngine._place_admitted",
                     "IterBatchingEngine._advance",
                     "IterBatchingEngine._advance_spec")

# Decode hot-loop scopes (tools/graftcheck host-sync rule): the segment
# dispatch loop is the zero-sync fast path; the spec variant's syncs are
# the documented per-segment price and are baselined.
GRAFTCHECK_HOT_LOOPS = ("IterBatchingEngine._advance",
                        "IterBatchingEngine._advance_spec")

# Fault contract (tools/graftcheck faults pass): the scheduler's two
# blocking boundaries. The caller's ``done.wait`` derives its budget
# from the request deadline (and cancellation frees the row's blocks at
# the next segment boundary); the worker's bare ``_queue.get`` is the
# idle park — deadlines are checked at every dequeue, so a stale
# request is failed typed instead of decoded for nobody.
FAULT_POLICY = {
    "done.wait": ("request", "none",
                  "cancel + free blocks at the next segment boundary"),
    "_queue.get": ("unbounded", "none",
                   "idle worker; deadline checked at dequeue"),
}

# Transient decode faults (graftfault.TransientFault — injected engine
# exceptions, and the class real transient device failures map to) park
# the live rows through the PR 5 recompute-resume path; a row that
# keeps faulting past this many parks fails typed instead of cycling
# forever.
FAULT_PARK_BUDGET = 3

# Timeline contract (tools/graftcheck timeline pass): the scheduler's
# lifecycle decisions land on the unified causal stream
# (utils/grafttime), rid-correlated — admission (seed/join), park
# (with its reason), preemption victim choice, recompute-resume, and
# the per-row fault-park-budget breaker state. Shared batched
# dispatches carry the live rid set via ``grafttime.correlate`` around
# the segment/seed dispatch regions (the fanout-span analog).
TIMELINE_EVENTS = {
    "admission": "_seed_batch / _admit_one_inner",
    "park": "_park_slot",
    "preempt": "_preempt_lowest",
    "resume": "_seed_batch / _admit_one_inner",
    "breaker": "_fault_park_all (per-row park-budget state)",
}

# HBM-ledger contract (tools/graftcheck memory pass + utils/graftmem):
# the live batch's long-lived device holdings, by graftmem component —
# both live on ``_BatchState`` (handle-keyed per batch). ``cache`` is
# the contiguous working cache (contiguous mode only: registered at
# seed, re-measured at grow/admit rebinds, released when a pool takes
# ownership of the state or the batch tears down); ``buf`` is the spec
# verify token buffer (spec batches only). Pool-mode block storage is
# the POOL's ledger entry (runtime/kv_pool.py) — tables hold ids, not
# bytes, so nothing double-counts.
MEMORY_LEDGER = {
    "cache": "engine_cache",
    "buf": "spec_buffers",
}

# Lock-discipline contract (tools/graftcheck locks pass): the scheduler
# counters AND the cross-thread scheduling state (``_parked`` parked
# rows, ``_pending`` held queue head) live under ``_stats_lock`` —
# serving threads read them through ``admission_load``/``stats`` while
# the worker mutates them, which is exactly the lost-update/stale-read
# window the pass exists to flag (the worker routes every touch through
# the tiny *_locked-discipline helpers below). ``_np`` is the lazily
# materialized host copy ``_SegOut`` guards with its own ``_lock``.
GUARDED_STATE = {
    "batches_run": "_stats_lock", "rows_served": "_stats_lock",
    "joins": "_stats_lock", "segments_run": "_stats_lock",
    "spec_segments_run": "_stats_lock", "eos_retires": "_stats_lock",
    "grows": "_stats_lock", "preemptions": "_stats_lock",
    "resumes": "_stats_lock", "fault_parks": "_stats_lock",
    "_parked": "_stats_lock",
    "_pending": "_stats_lock",
    "_np": "_lock",
}

# ``_stats_lock`` holds are leaf-scoped (list/counter ops only) and the
# _SegOut fetch lock never nests inside them; the declared order keeps
# it that way.
LOCK_ORDER = ("_stats_lock", "_lock")


def _rid_of(req) -> Optional[str]:
    """The request's timeline correlator (its trace's X-Request-ID);
    None for untraced engine-level calls."""
    return getattr(req.trace, "request_id", None)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@dataclasses.dataclass
class _Req:
    prompt: np.ndarray
    max_new_tokens: int
    sampling: SamplingConfig
    key: Optional[jax.Array]
    eos_id: Optional[int]
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    payload: Optional[tuple] = None   # (_Slot, eos_at) — caller assembles
    error: Optional[Exception] = None
    # Set by generate() on timeout: the caller is gone, so the scheduler
    # drops the request at dequeue and frees its slot at the next
    # retirement pass instead of decoding dead tokens for nobody.
    cancelled: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    # per-request deadline budget (graftfault.Deadline): checked at
    # every dequeue and segment boundary — a past-deadline request/row
    # is failed typed and its blocks freed, never decoded for nobody
    deadline: Optional[graftfault.Deadline] = None
    # request-trace propagation (caller's ambient RequestTrace): the
    # scheduler stamps queue wait, the admission prefill, and every
    # decode segment the row rode into it
    trace: Optional[object] = None
    t_submit: float = 0.0

    def fail(self, e: Exception) -> None:
        """Deliver an error exactly once (idempotent across the several
        except paths that may observe the same request)."""
        if not self.done.is_set():
            self.error = e
            self.done.set()


class _SegOut:
    """One segment's [B, n] token output, fetched to host at most once
    (several retiring rows may share it; caller threads race the fetch,
    hence the lock). The device->host copy starts ASYNC at construction
    so it overlaps later segments — by delivery time it is usually
    already resident."""

    def __init__(self, arr):
        self.arr = arr
        self._np = None
        self._lock = graftsched.lock("iterbatch._SegOut._lock")
        try:
            arr.copy_to_host_async()
        except AttributeError:  # non-jax array (tests)
            pass

    @property
    def np(self) -> np.ndarray:
        with self._lock:
            if self._np is None:
                # OWNING copy, not np.asarray: on the CPU backend
                # np.asarray returns a ZERO-COPY view of the device
                # buffer, and once the next segment DONATES the array
                # XLA may rewrite that memory in place under the view —
                # the snapshot would silently shift (observed as
                # rolled-buffer corruption in parked spec rows)
                self._np = np.array(self.arr, copy=True)
            return self._np


@dataclasses.dataclass
class _Slot:
    req: _Req
    plen: int
    row: int                      # this slot's batch row index (fixed)
    first_ref: Optional["_SegOut"]  # holds the first generated token ...
    first_idx: int                # ... at this index (None for resumed
                                  # rows: resumed_prefix replaces it)
    dk: Optional[jax.Array]       # per-row decode key (sample mode)
    emitted: int = 1              # tokens generated so far (incl. first)
    segs: List = dataclasses.field(default_factory=list)  # (_SegOut, n)
    # admission order: THE preemption priority (higher = admitted later
    # = preempted first). Monotonic across the scheduler's lifetime.
    order: int = 0
    # pool mode: this row's block ids at table columns
    # [blk_lo, blk_lo + len(blk_ids)) — everything outside points at
    # the trash block
    blk_lo: int = 0
    blk_ids: List[int] = dataclasses.field(default_factory=list)
    # tokens emitted before a preemption (host copy); delivery prepends
    # them in place of first_ref
    resumed_prefix: Optional[np.ndarray] = None
    # Spec-mode delivery state: the latest segment's [B, buflen] token
    # buffer (prompt + everything emitted, per row, left-aligned at the
    # row's pad) and this row's pad at that moment — _row_tokens reads
    # the stream straight out of it, no per-segment part list needed.
    spec_buf: Optional["_SegOut"] = None
    spec_pad: int = 0
    # transient-fault parks this row has already absorbed (graftfault):
    # past FAULT_PARK_BUDGET the row fails typed instead of re-parking
    fault_budget_used: int = 0
    t0: float = 0.0
    done_t: float = 0.0


def _admit_cache_impl(cache, solo, slot, roll):
    """Merge a solo-prefilled row into batch slot ``slot``: the row's
    K/V content rolls from solo slots ``[sp - plen, sp)`` to the batch's
    ``[d - plen, d)`` (``roll = d - sp``; wrap garbage lands in the
    masked pad prefix or in not-yet-written slots that decode overwrites
    before reading). ``slot``/``roll`` are traced scalars — one compiled
    program serves every admission. Handles plain, fused (placeholder
    ``v``), and staged (list) cache forms."""
    def one(c: KVCache, s: KVCache) -> KVCache:
        k = jax.lax.dynamic_update_slice_in_dim(
            c.k, jnp.roll(s.k, roll, axis=-2), slot, axis=1)
        if getattr(c.v, "ndim", 0) <= 1:      # fused cache: v placeholder
            v = c.v
        else:
            v = jax.lax.dynamic_update_slice_in_dim(
                c.v, jnp.roll(s.v, roll, axis=-2), slot, axis=1)
        return KVCache(k=k, v=v, length=c.length)

    if isinstance(cache, list):
        return [one(c, s) for c, s in zip(cache, solo)]
    return one(cache, solo)


def _admit_cache_scope_key(cache, solo, slot, roll):
    """Program key: (batch width, cache width, solo width) — slot/roll
    are traced and never key programs."""
    c = cache[0] if isinstance(cache, list) else cache
    s = solo[0] if isinstance(solo, list) else solo
    return (int(c.k.shape[1]), int(c.k.shape[-2]), int(s.k.shape[-2]))


_admit_cache = graftscope.instrument(
    jax.jit(_admit_cache_impl, donate_argnums=(0,)),
    "iterbatch._admit_cache", key_fn=_admit_cache_scope_key)


@dataclasses.dataclass
class _Parked:
    """A preempted row between its park and its resume: everything the
    recompute path needs to reproduce the stream byte-identically."""

    req: _Req
    plen: int
    emitted: int                  # tokens generated before the park
    tokens: np.ndarray            # those tokens, fetched to host
    order: int                    # original admission order (priority)
    t0: float                     # original admission wall-clock
    preempt_t: float = 0.0
    spec_key: Optional[np.ndarray] = None  # verify key chain (spec rows)
    fault_budget_used: int = 0    # transient-fault parks absorbed so far


class _BatchState:
    """The live batch between segments (worker-thread-only state)."""

    def __init__(self, sampling, token, cache, pad_j, depth):
        self.sampling = sampling
        self.token = token            # [B] device
        self.cache = cache            # contiguous mode only; None when a
                                      # pool owns the state between
                                      # segments (tables instead)
        self.pad_j = pad_j            # [B] device int32
        self.depth = depth            # uniform cache depth (host int)
        self.tables: Optional[np.ndarray] = None   # [B, NBm] (pool mode)
        self.slots: List[Optional[_Slot]] = []
        self.closed = False           # True: no more admissions (FIFO)
        # speculative batches only: device token buffer [B, buflen]
        # (prompt + emitted per row, content ending at depth + 1) and
        # the per-row verify key chains [B, 2] (sample mode)
        self.spec_mode = False
        self.buf = None
        self.keys = None
        # HBM ledger handles (utils/graftmem): released by _run_batch
        # at batch teardown (the owner finalizer backstops any path
        # that drops the state without reaching it)
        self.mem_cache = (graftmem.track(self, "cache", "engine_cache",
                                         cache)
                          if cache is not None else 0)
        self.mem_buf = 0

    def active(self):
        return any(s is not None for s in self.slots)


class IterBatchingEngine:
    """Thread-safe iteration-level batching front end over a
    ``DecodeEngine`` (same calling convention as ``BatchingEngine``).

    ``seg_steps`` is the scheduling granularity: admissions and
    retirements happen every ``seg_steps`` decode steps. Smaller = lower
    join latency, more scheduler work; larger = better dispatch
    pipelining. A request's worst-case join delay is one segment.
    """

    def __init__(self, engine: DecodeEngine, max_batch: int = 8,
                 seg_steps: int = 32, max_wait_ms: float = 2.0,
                 prompt_bucket: int = 16, spec=None, prefix=None,
                 pool=None, queue_limit: Optional[int] = None,
                 replica: Optional[str] = None):
        """``spec`` (optional ``SpecDecodeEngine`` wrapping THIS engine)
        enables speculative segments: batches whose policy carries
        ``SamplingConfig.spec`` advance by draft-verify forwards instead
        of single-token steps (see module docstring). ``prefix``
        (optional ``PrefixCachingEngine`` wrapping THIS engine) routes
        admission prefills through the prefix store, so a joiner with a
        warm prefix forwards only its suffix.

        ``pool`` (optional ``runtime.kv_pool.KVBlockPool`` matching THIS
        engine's cache geometry) turns on paged KV storage, watermark
        admission, and preemption/resume (module docstring).
        ``queue_limit`` feeds ``admission_load`` (the serving 429
        decision): with the pool unable to host a request AND at least
        this many requests already waiting/parked, serving sheds load
        instead of queueing unboundedly. Defaults to ``max_batch``.

        ``replica`` labels the worker thread's timeline events
        (grafttime's replica correlator): the serving handler's
        ambient label is a contextvar on ITS thread, so without this
        the scheduler-side events (admission/park/resume/dispatch)
        would carry no replica in a fleet's unified stream."""
        from ..models import is_window_independent
        if not is_window_independent(engine.config):
            raise NotImplementedError(
                "iteration-level batching requires window-independent "
                "routing (a joined MoE row's tokens could depend on "
                "batch composition); MoE serves via the admission "
                "batcher")
        if engine.prefill_chunk:
            raise NotImplementedError(
                "iteration-level batching prefills admissions solo at "
                "bucketed lengths; it does not compose with "
                "prefill_chunk (use the admission batcher)")
        if engine._mesh is not None:
            raise NotImplementedError(
                "iteration-level batching drives the single-device "
                "engine; mesh decode (tp/ep) uses the admission batcher")
        if spec is not None and spec.plain is not engine:
            raise ValueError("spec must wrap the same DecodeEngine (shared "
                             "weights/programs), got a different instance")
        if prefix is not None and prefix.plain is not engine:
            raise ValueError("prefix must wrap the same engine instance")
        if pool is not None and pool.max_seq != engine._cache_seq:
            raise ValueError(
                f"pool rows span {pool.max_seq} slots, engine cache is "
                f"{engine._cache_seq}; gathered segments must match the "
                "compiled programs' cache width")
        self.engine = engine
        self.spec = spec
        self.prefix = prefix
        self.pool = pool
        self.queue_limit = max_batch if queue_limit is None else queue_limit
        self.replica = replica
        self.max_batch = max_batch
        self.seg_steps = seg_steps
        self.max_wait_s = max_wait_ms / 1e3
        self.prompt_bucket = prompt_bucket
        self._queue: "queue.Queue[_Req]" = queue.Queue()
        self._pending: Optional[_Req] = None
        self._parked: List[_Parked] = []   # preempted rows, oldest first
        self._order = 0                    # admission-order counter
        #                                    (worker-thread-only)
        self._stats_lock = graftsched.lock(
            "iterbatch.IterBatchingEngine._stats_lock")
        self.batches_run = 0
        self.rows_served = 0
        self.joins = 0                # admissions into a LIVE batch
        self.segments_run = 0
        self.spec_segments_run = 0    # draft-verify segments (spec mode)
        self.eos_retires = 0
        self.grows = 0                # width upgrades of a live batch
        self.preemptions = 0          # rows parked under pool pressure
        self.resumes = 0              # parked rows recomputed back in
        self.fault_parks = 0          # transient-fault park events
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    # -- caller side ---------------------------------------------------------

    def generate(self, prompt_ids, max_new_tokens: int,
                 sampling: SamplingConfig = SamplingConfig(),
                 key: Optional[jax.Array] = None,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None,
                 deadline: Optional[graftfault.Deadline] = None,
                 ) -> GenerateResult:
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must be non-empty")
        if len(prompt) + max_new_tokens > self.engine.max_seq:
            raise ValueError(
                f"prompt_len={len(prompt)} + max_new_tokens="
                f"{max_new_tokens} exceeds max_seq={self.engine.max_seq}")
        if sampling.mode != "greedy" and key is None:
            raise ValueError(
                "sample-mode requests must carry a per-request PRNG key")
        if sampling.spec:
            # caller-thread eligibility: a spec-flagged request the
            # verify loop cannot serve exactly must be refused HERE with
            # its own numbers, not discovered mid-batch (rule defined
            # once, on the engine)
            if self.spec is None:
                raise ValueError(
                    "sampling.spec requested but this scheduler has no "
                    "speculative engine attached (pass spec= at "
                    "construction)")
            self.spec.check_request(len(prompt), max_new_tokens)
        if deadline is not None:
            deadline.raise_if_expired("iter-batched generate")
        req = _Req(prompt=prompt, max_new_tokens=max_new_tokens,
                   sampling=sampling, key=key, eos_id=eos_id,
                   deadline=deadline,
                   trace=tracing.current_trace(),
                   t_submit=time.perf_counter())
        self._queue.put(req)
        REGISTRY.gauge("queue_depth", self._queue.qsize(),
                       scheduler="iter")
        # the caller's wait derives from the remaining deadline budget:
        # HTTP wait is the first leg the budget bounds end-to-end
        wait = timeout
        if deadline is not None:
            rem = deadline.remaining()
            wait = rem if wait is None else min(wait, rem)
        if not req.done.wait(wait):
            # Cancel, don't just abandon: the scheduler skips cancelled
            # requests at dequeue and retires a cancelled live row at the
            # next segment boundary, so repeated timeouts cannot
            # accumulate dead decode work (ADVICE r4).
            req.cancelled.set()
            if deadline is not None and deadline.expired():
                raise graftfault.DeadlineExceeded(
                    "iter-batched generate: deadline budget exhausted; "
                    "in-flight work is cancelled at the next segment "
                    "boundary and its blocks freed")
            raise TimeoutError("iter-batched generate timed out")
        if req.error is not None:
            raise req.error
        # token assembly (the device->host fetches) happens HERE, on the
        # caller's thread: the scheduler thread only marks rows done, so
        # it never blocks on a transfer and keeps dispatching segments.
        # The async copies started at segment creation usually make this
        # a no-wait read.
        s, eos_at = req.payload
        new = self._row_tokens(s)
        if eos_at is not None:
            new = new[:eos_at + 1]
        tokens = np.concatenate([req.prompt, new])[None, :]
        # Timing caveat: the scheduler never syncs per phase, so
        # decode_seconds here is the row's WALL time from admission to
        # retirement (prefill + shared segments + scheduling), not a
        # pure decode window — an honest end-to-end number, but do not
        # read tokens_per_second as a device decode rate.
        return GenerateResult(
            tokens=tokens, prompt_len=s.plen,
            prefill_seconds=0.0, decode_seconds=s.done_t - s.t0,
            new_tokens=len(new), decode_steps=len(new) - 1)

    def stats(self) -> dict:
        with self._stats_lock:
            out = {"batches": self.batches_run, "rows": self.rows_served,
                   "joins": self.joins, "segments": self.segments_run,
                   "spec_segments": self.spec_segments_run,
                   "eos_retires": self.eos_retires, "grows": self.grows,
                   "preemptions": self.preemptions,
                   "resumes": self.resumes,
                   "fault_parks": self.fault_parks,
                   "parked": len(self._parked)}
        return out

    def admission_load(self, prompt_len: int,
                       max_new_tokens: int) -> Tuple[bool, float]:
        """The serving 429 decision: can this request reasonably be
        queued, or is the pool saturated AND the queue already at its
        limit (sustained overload — shed with Retry-After)? Always
        admits without a pool (the pre-pool unbounded-queue behavior)."""
        if self.pool is None:
            return True, 0.0
        # admission footprint (the prefill's blocks) — growth past it is
        # the preemption machinery's business, not the 429 gate's.
        # ``can_admit`` here is ADVISORY (load shedding): the worker's
        # actual grant goes through the atomic ``admit_alloc`` path, so
        # a stale answer costs one queue beat, never a request failure.
        need = self.pool.allocator.blocks_for(prompt_len)
        with self._stats_lock:
            waiting = (self._queue.qsize() + len(self._parked)
                       + (1 if self._pending is not None else 0))
        # seeded pool-exhaustion spike (graftfault): the 429 gate sheds
        # exactly as it would under a real capacity storm, so the shed
        # path (Retry-After plausibility, rejection counter, allocator
        # conservation) is testable deterministically
        spike = graftfault.inject("iterbatch.admission_load",
                                  "pool_spike")
        if spike is None and (self.pool.allocator.can_admit(need)
                              or waiting < self.queue_limit):
            return True, 0.0
        # crude but honest: each max_batch-wide wave of waiters needs
        # roughly one batch lifetime to drain
        return False, float(1 + waiting // max(self.max_batch, 1))

    # -- worker side ---------------------------------------------------------

    # The worker owns ``_parked``/``_pending`` mutation, but serving
    # threads read both (``admission_load``, ``stats``) — so EVERY touch
    # goes through these leaf-locked helpers (the locks-pass
    # unguarded-state contract; before this discipline, ``stats`` read
    # ``_parked`` under ``_stats_lock`` while the worker mutated it with
    # no lock at all — guarded in one place and bare in another).

    def _peek_parked(self) -> Optional[_Parked]:
        with self._stats_lock:
            return self._parked[0] if self._parked else None

    def _pop_parked(self) -> Optional[_Parked]:
        with self._stats_lock:
            return self._parked.pop(0) if self._parked else None

    def _park(self, parked: _Parked) -> None:
        # oldest-first resume order (sorted by admission order)
        with self._stats_lock:
            self._parked.append(parked)
            self._parked.sort(key=lambda p: p.order)

    def _take_pending(self) -> Optional[_Req]:
        with self._stats_lock:
            req, self._pending = self._pending, None
            return req

    def _get_pending(self) -> Optional[_Req]:
        with self._stats_lock:
            return self._pending

    def _set_pending(self, req: Optional[_Req]) -> None:
        with self._stats_lock:
            self._pending = req

    def _req_dead(self, req: _Req) -> bool:
        """Cancelled OR past its deadline — either way nobody wants the
        work. A past-deadline request is failed typed here (idempotent:
        the caller usually raised at its own wait expiry already) and
        marked cancelled so every later checkpoint skips it."""
        if req.cancelled.is_set():
            return True
        if req.deadline is not None and req.deadline.expired():
            req.fail(graftfault.DeadlineExceeded(
                "deadline budget exhausted before the scheduler could "
                "run this request"))
            req.cancelled.set()
            return True
        return False

    def _loop(self):
        if self.replica is not None:
            # the worker thread's OWN context: every timeline event it
            # emits carries this app's replica label (the handler
            # thread's ambient label does not propagate here)
            grafttime.set_thread_replica(self.replica)
        while True:
            # parked rows outrank every queued request (they were
            # admitted first — FIFO priority): with any parked, the next
            # batch seeds from the parked head instead of the queue
            head = self._pop_parked()
            if head is not None:
                if self._req_dead(head.req):
                    continue
            else:
                head = self._take_pending()
                if head is None:
                    head = self._queue.get()
                if self._req_dead(head):
                    continue
            try:
                self._run_batch(head)
            except Exception as e:  # noqa: BLE001 — delivered per-request
                (head.req if isinstance(head, _Parked) else head).fail(e)

    def _compatible(self, state: _BatchState, ent) -> bool:
        """Can this entry (a fresh ``_Req`` or a ``_Parked`` resume)
        join the live batch right now? ONE predicate for both — a
        policy constraint added here gates resumes and fresh arrivals
        identically. Policy must match (the ``spec`` flag included — a
        spec arrival never joins a plain batch or vice versa), the
        tokens its prefill forwards must fit the current depth (content
        at ``[d - plen', d)``), and its remaining generation must fit
        the cache — with ``draft_len`` extra slots of verify-write
        headroom when the batch speculates. Pool room is checked
        SEPARATELY (``_reserve_blocks`` / ``admit_alloc``): a policy
        mismatch closes admission, missing pool room only defers it."""
        reserve = self.spec.draft_len if state.spec_mode else 0
        return (self._ent_req(ent).sampling == state.sampling
                and len(self._ent_ids(ent)) <= state.depth
                and state.depth + self._ent_need(ent) + reserve
                <= self.engine.max_seq)

    def _run_batch(self, head: _Req):
        state = self._seed(head)
        try:
            while state.active():
                if not state.closed:
                    self._admit(state)
                try:
                    # the segment dispatch serves every live row: its
                    # instrumented dispatches (and any fault injected
                    # inside) carry the live rid set on the timeline
                    with grafttime.correlate(
                            [_rid_of(s.req) for s in state.slots
                             if s is not None]):
                        self._advance(state)
                except graftfault.TransientFault as e:
                    # degraded mode: a transient decode fault parks
                    # every live row through the PR 5 recompute-resume
                    # path — resumed streams are byte-identical; a row
                    # past its park budget fails typed (503) instead of
                    # cycling forever
                    self._fault_park_all(state, e)
        except Exception as e:  # noqa: BLE001
            for i, s in enumerate(state.slots):
                if s is not None:
                    s.req.fail(e)
                    # an aborted batch must hand its pool blocks back —
                    # the normal retire/cancel/preempt release paths
                    # never run for these slots, and leaked refs would
                    # shrink the pool permanently
                    self._release_blocks(state, i)
            raise
        finally:
            # batch teardown: its device holdings leave the HBM ledger
            # (an idle scheduler must not keep reporting the last
            # batch's cache/buffer bytes)
            graftmem.release(state.mem_cache)
            graftmem.release(state.mem_buf)

    # -- seeding -------------------------------------------------------------

    @staticmethod
    def _ent_req(e) -> _Req:
        return e.req if isinstance(e, _Parked) else e

    @staticmethod
    def _ent_ids(e) -> np.ndarray:
        """The tokens a seed/admission prefill forwards for this entry:
        the prompt, or — resuming a parked row — prompt + all emitted
        tokens but the last (the last is the live, not-yet-forwarded
        token the segment loop carries)."""
        if isinstance(e, _Parked):
            return np.concatenate([e.req.prompt, e.tokens[:-1]])
        return e.prompt

    @staticmethod
    def _ent_need(e) -> int:
        """Cache slots the entry still needs past its prefill."""
        if isinstance(e, _Parked):
            return e.req.max_new_tokens - e.emitted + 1
        return e.max_new_tokens

    def _seed(self, head) -> _BatchState:
        """Start a batch: gather same-policy parked rows first (they
        outrank every queued request), then up-to-``max_wait`` queued
        peers that fit. Any failure past the gathering point (e.g. a
        prefill OOM) is delivered to EVERY gathered request, not just
        the head — a gathered peer with ``done`` never set would block
        its caller forever (ADVICE r4 medium)."""
        seed = [head]
        sampling = self._ent_req(head).sampling
        while len(seed) < self.max_batch:
            nxt = self._peek_parked()
            if nxt is None:
                break
            if self._req_dead(nxt.req):
                self._pop_parked()
                continue
            if (nxt.req.sampling == sampling
                    and self._fits(seed + [nxt])):
                seed.append(self._pop_parked())
            else:
                break  # stays parked; reconsidered at admission/next seed
        deadline = time.monotonic() + self.max_wait_s
        while len(seed) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if self._req_dead(nxt):
                continue
            if nxt.sampling == sampling and self._fits(seed + [nxt]):
                seed.append(nxt)
            else:
                # incompatible arrival: parked as the FIFO head — _admit
                # reconsiders it first (it may fit once the batch is
                # live) and otherwise it seeds the next batch
                self._set_pending(nxt)
                break
        try:
            return self._seed_batch(seed)
        except Exception as e:  # noqa: BLE001
            for r in seed:
                self._ent_req(r).fail(e)
            raise

    def _seed_batch(self, seed: List) -> _BatchState:
        eng = self.engine
        sampling = self._ent_req(seed[0]).sampling
        spec_mode = sampling.spec
        s_max = self._seed_smax(seed)
        rows = [self._ent_ids(e) for e in seed]

        # Right-size the compiled width (ADVICE r4: a lone request must
        # not pay max_batch x prefill/decode FLOPs for ghost rows): the
        # batch runs at the next power of two that fits the seed, and
        # _admit grows it on demand. Width set = {1, 2, 4, ..,
        # max_batch} — a bounded extra-program inventory.
        b = min(_next_pow2(len(seed)), self.max_batch)
        # timeline: the admission/resume DECISION happens here, at
        # gather time — before the seed prefill dispatch it causes
        for e in seed:
            r = self._ent_req(e)
            if isinstance(e, _Parked):
                grafttime.emit("resume", rid=_rid_of(r),
                               emitted=e.emitted, mode="seed", width=b)
            else:
                grafttime.emit("admission", rid=_rid_of(r), mode="seed",
                               width=b, prompt_len=len(r.prompt))
        ids = np.zeros((b, s_max), dtype=np.int32)
        pad = np.zeros((b,), dtype=np.int32)
        for i in range(b):
            row = rows[min(i, len(seed) - 1)]  # free slots replicate last
            ids[i, s_max - len(row):] = row
            pad[i] = s_max - len(row)
        ids_j = jnp.asarray(ids)
        pad_j = jnp.asarray(pad)

        t0 = time.monotonic()
        sp0 = time.perf_counter()
        run_params = eng._run_params()
        # the shared seed prefill serves every gathered request: its
        # instrumented dispatches carry the whole rid set (grafttime)
        with grafttime.correlate([_rid_of(self._ent_req(e))
                                  for e in seed]):
            last_logits, cache = eng._prefill(run_params, ids_j, pad_j)
        first, pks, dks = self._first_tokens(
            last_logits, sampling, [self._ent_req(e).key for e in seed], b)
        # Resumed rows: the "first" token is the parked row's last
        # emitted token — KNOWN, never re-selected (greedy would
        # reproduce it from the recomputed logits; a sampled row's draw
        # came from an earlier step key, so the override is what makes
        # the resumed stream byte-identical).
        for i, e in enumerate(seed):
            if isinstance(e, _Parked):
                first = first.at[i].set(int(e.tokens[-1]))
        sp1 = time.perf_counter()
        for e in seed:
            r = self._ent_req(e)
            if r.trace is not None:
                if isinstance(e, _Parked):
                    r.trace.add_span("preempted", e.preempt_t, sp0,
                                     scheduler="iter")
                    r.trace.add_span("prefill", sp0, sp1, kind="resume",
                                     width=b, emitted=e.emitted)
                else:
                    r.trace.add_span("queue_wait", r.t_submit, sp0,
                                     scheduler="iter")
                    r.trace.add_span("prefill", sp0, sp1, kind="seed",
                                     width=b, prompt_len=len(r.prompt))

        state = _BatchState(sampling, first, cache, pad_j, s_max)
        if spec_mode:
            # verify-loop entry state (spec_decode._seg_b invariant): the
            # token buffer holds prompt + the unforwarded first token per
            # row, content at [pad_b, depth + 1); the per-row key chains
            # are the dks the solo loop would carry (split(key)[1]) —
            # except resumed rows, whose chains advanced with every
            # verify step and resume from the parked snapshot.
            buf = jnp.zeros((b, eng.max_seq + self.spec.draft_len + 1),
                            jnp.int32)
            buf = jax.lax.dynamic_update_slice(buf, ids_j, (0, 0))
            buf = jax.lax.dynamic_update_slice(buf, first[:, None],
                                               (0, s_max))
            state.spec_mode = True
            state.buf = buf
            state.mem_buf = graftmem.track(state, "buf", "spec_buffers",
                                           buf)
            keys = (dks if dks is not None
                    else jnp.zeros((b, 2), jnp.uint32))
            for i, e in enumerate(seed):
                if isinstance(e, _Parked) and e.spec_key is not None:
                    keys = keys.at[i].set(jnp.asarray(e.spec_key))
            state.keys = keys
        first_ref = _SegOut(first)          # one shared [B] fetch
        state.slots = [None] * b
        n_res = 0
        for i, e in enumerate(seed):
            r = self._ent_req(e)
            if isinstance(e, _Parked):
                n_res += 1
                state.slots[i] = _Slot(
                    req=r, plen=e.plen, row=i, first_ref=None,
                    first_idx=0, dk=None if dks is None else dks[i],
                    emitted=e.emitted, resumed_prefix=e.tokens,
                    order=e.order, t0=e.t0,
                    fault_budget_used=e.fault_budget_used)
            else:
                self._order += 1
                state.slots[i] = _Slot(req=r, plen=len(r.prompt), row=i,
                                       first_ref=first_ref, first_idx=i,
                                       dk=None if dks is None else dks[i],
                                       order=self._order, t0=t0)
        if self.pool is not None:
            self._init_tables(state)
        with self._stats_lock:
            self.batches_run += 1
            self.resumes += n_res
        REGISTRY.inc("iter_batches_total")
        if n_res:
            REGISTRY.inc("kv_pool_resumes_total", value=n_res)
        self.engine._note_compiles()
        self._retire_finished(state)      # max_new_tokens == 1 rows
        self._set_gauges(state)
        return state

    def _fits(self, ents: List) -> bool:
        s_max = self._seed_smax(ents)
        ok = all(s_max + self._ent_need(e) + self._reserve(ents[0])
                 <= self.engine.max_seq
                 and len(self._ent_ids(e)) <= s_max for e in ents)
        if ok and self.pool is not None:
            # CURRENT footprint only (blocks covering the seed depth):
            # admission deliberately OVERSUBSCRIBES future growth — that
            # is what preemption is for; a worst-case check here would
            # forbid exactly the concurrency the pool exists to raise
            alloc = self.pool.allocator
            need = sum(
                alloc.blocks_for(s_max)
                - (s_max - len(self._ent_ids(e))) // self.pool.block_size
                for e in ents)
            ok = need <= alloc.available()
        return ok

    def _reserve(self, ent) -> int:
        """Cache slots held back beyond the generation: speculative
        batches need ``draft_len`` of verify-write headroom past the
        deepest content slot (the spec engine's own guard, applied to
        the batch's shared shape)."""
        return (self.spec.draft_len
                if self._ent_req(ent).sampling.spec else 0)

    def _seed_smax(self, ents: List) -> int:
        raw = max(len(self._ent_ids(e)) for e in ents)
        need = max(self._ent_need(e) for e in ents)
        return min(_round_up(raw, self.prompt_bucket),
                   self.engine.max_seq - need - self._reserve(ents[0]))

    def _first_tokens(self, last_logits, sampling, keys, b):
        """First-token selection + per-row (prefill, decode) key split.
        Free slots get zero keys (their draws are dropped)."""
        if sampling.mode == "greedy":
            first = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            return first, None, None
        ks = [jnp.asarray(k) for k in keys]
        ks += [jnp.zeros_like(ks[0])] * (b - len(ks))
        stack = jnp.stack(ks)                       # [b, 2]
        pair = jax.vmap(jax.random.split)(stack)    # [b, 2, 2]
        pks, dks = pair[:, 0], pair[:, 1]
        first = select_token(last_logits, sampling, pks)
        return first, pks, dks

    # -- admission -----------------------------------------------------------

    def _reserve_blocks(self, state: _BatchState, ent):
        """ATOMIC pool admission for one would-be row's CURRENT
        footprint — blocks covering its content at the live depth
        (pad-prefix blocks are free, they point at trash). Growth past
        this is deliberately oversubscribed: preemption handles it.

        The watermark check and the grant run under ONE allocator lock
        hold (``BlockAllocator.admit_alloc``): the old two-step
        ``can_admit`` -> later ``alloc`` left a window where a
        concurrent pool user (the prefix store's insert, a solo paged
        runner sharing the pool) could take the checked blocks, turning
        a deferrable admission into a ``PoolExhausted`` request failure
        — or, raced the other way, an over-watermark grant (the
        graftsched check-then-act fixture pins both shapes). Returns
        ``(p_lo, granted ids)`` or None to defer (blocks free up as
        rows retire)."""
        if self.pool is None:
            return 0, []
        alloc = self.pool.allocator
        plen_eff = len(self._ent_ids(ent))
        p_lo = (state.depth - plen_eff) // self.pool.block_size
        p_hi = -(-state.depth // self.pool.block_size)
        ids = alloc.admit_alloc(p_hi - p_lo)
        if ids is None:
            return None
        return p_lo, ids

    def _admit(self, state: _BatchState):
        """Drain parked rows (oldest first — they outrank the queue),
        then compatible queued requests, into free slots. Strict FIFO:
        an incompatible head closes admission for this batch and seeds
        the next one — EXCEPT a head that is policy-compatible but
        lacks pool room, which stays waiting without closing (blocks
        free up as rows retire; closing would thrash batches under
        memory pressure). A request parked in ``_pending`` (by
        ``_seed`` or a previous round) is ALWAYS the queue's head — it
        is reconsidered first and never overwritten, so no request can
        be dropped. When the right-sized batch has no free slot but is
        narrower than ``max_batch``, the live batch GROWS to the next
        power of two (ghost rows replicate row 0; per-row exactness
        makes them inert) instead of turning the arrival away."""
        while True:
            ent = self._peek_parked()
            if ent is None:
                break
            if self._req_dead(ent.req):
                self._pop_parked()
                continue
            if not self._compatible(state, ent):
                # the parked head must not be overtaken by younger
                # queued requests: a policy mismatch closes admission
                # (it seeds the next batch); a depth/headroom mismatch
                # just waits for the next batch to seed from it
                if ent.req.sampling != state.sampling:
                    state.closed = True
                return
            if not self._slot_possible(state):
                return  # full batch: retried at the next boundary
            reserved = self._reserve_blocks(state, ent)
            if reserved is None:
                return  # blocks free up as rows retire; stays parked
            slot = self._free_slot(state)
            if slot is None:
                if self.pool is not None:
                    self.pool.allocator.free(reserved[1])
                return
            ent = self._pop_parked()
            try:
                self._admit_one(state, ent.req, slot, resume=ent,
                                reserved=reserved)
            except Exception as e:  # noqa: BLE001
                ent.req.fail(e)
                raise
        while True:
            req = self._get_pending()
            if req is None:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    return
                self._set_pending(req)
            if self._req_dead(req):
                self._set_pending(None)
                continue
            if not self._compatible(state, req):
                state.closed = True  # req stays parked as the FIFO head
                return
            if not self._slot_possible(state):
                return  # full batch: req stays the head
            reserved = self._reserve_blocks(state, req)
            if reserved is None:
                return  # req stays the head; retried as rows retire
            slot = self._free_slot(state)
            if slot is None:
                if self.pool is not None:
                    self.pool.allocator.free(reserved[1])
                return
            self._set_pending(None)
            try:
                self._admit_one(state, req, slot, reserved=reserved)
            except Exception as e:  # noqa: BLE001 — the popped request is
                req.fail(e)        # not in state.slots yet; without this
                raise              # its caller would block forever

    def _slot_possible(self, state: _BatchState) -> bool:
        """Could an admission find (or grow into) a slot right now?
        Checked BEFORE reserving pool blocks: ``admit_alloc`` may evict
        zero-ref prefix entries to satisfy a grant, and reserving for a
        full, ungrowable batch would thrash the prefix cache for a
        grant that is immediately handed back."""
        return (any(s is None for s in state.slots)
                or len(state.slots) < self.max_batch)

    def _free_slot(self, state: _BatchState) -> Optional[int]:
        free = [i for i, s in enumerate(state.slots) if s is None]
        if not free:
            if len(state.slots) >= self.max_batch:
                return None  # full: retried at the next boundary
            self._grow(state)
            free = [i for i, s in enumerate(state.slots) if s is None]
        return free[0]


    def _grow(self, state: _BatchState):
        """Widen the live batch to the next power of two: pad token /
        pad_j / cache along the batch axis by replicating row 0 (any
        live content is valid ghost material — rows are independent).
        One tiny concat program per (width, cache-shape) pair, from the
        same bounded width set as the decode programs."""
        old = len(state.slots)
        new = min(_next_pow2(old + 1), self.max_batch)
        pad_rows = new - old

        def rep(x, axis):
            return jnp.concatenate(
                [x, jnp.repeat(jax.lax.slice_in_dim(x, 0, 1, axis=axis),
                               pad_rows, axis=axis)], axis=axis)

        def grow_cache(c):
            def one(kc: KVCache) -> KVCache:
                v = kc.v if getattr(kc.v, "ndim", 0) <= 1 else rep(kc.v, 1)
                return KVCache(k=rep(kc.k, 1), v=v, length=kc.length)
            if isinstance(c, list):
                return [one(x) for x in c]
            return one(c)

        state.token = rep(state.token, 0)
        state.pad_j = rep(state.pad_j, 0)
        if state.cache is not None:
            state.cache = grow_cache(state.cache)
            graftmem.update(state.mem_cache, state.cache)
        if state.tables is not None:
            # ghost lanes read (and scatter) the trash block only
            state.tables = np.concatenate(
                [state.tables,
                 np.full((pad_rows, self.pool.nbm), self.pool.trash,
                         dtype=np.int32)], axis=0)
        if state.spec_mode:
            # ghost rows clone row 0's buffer/key lane; their zero
            # budgets keep them inert through every verify (n_emit = 0)
            state.buf = rep(state.buf, 0)
            graftmem.update(state.mem_buf, state.buf)
            state.keys = rep(state.keys, 0)
        state.slots = state.slots + [None] * pad_rows
        with self._stats_lock:
            self.grows += 1
        REGISTRY.inc("iter_grows_total")

    def _admit_one(self, state: _BatchState, req: _Req, slot: int,
                   resume: Optional[_Parked] = None,
                   reserved: Optional[Tuple[int, List[int]]] = None):
        """``reserved`` (pool mode) is the row's atomically pre-granted
        block reservation from ``_reserve_blocks`` — this function owns
        it: consumed by ``_place_admitted`` on success, freed on ANY
        failure in between (a prefill OOM must not leak the grant)."""
        try:
            return self._admit_one_inner(state, req, slot, resume,
                                         reserved)
        except BaseException:
            if self.pool is not None and reserved is not None:
                self.pool.allocator.free(reserved[1])
                if state.tables is not None:
                    state.tables[slot, :] = self.pool.trash
            raise

    def _admit_one_inner(self, state: _BatchState, req: _Req, slot: int,
                         resume: Optional[_Parked],
                         reserved: Optional[Tuple[int, List[int]]]):
        eng = self.engine
        stream = self._ent_ids(resume) if resume is not None else req.prompt
        plen_eff = len(stream)            # tokens the prefill forwards
        # timeline: the join/resume DECISION happens here — before the
        # admit prefill dispatch it causes
        if resume is not None:
            grafttime.emit("resume", rid=_rid_of(req),
                           emitted=resume.emitted, mode="join",
                           depth=state.depth)
        else:
            grafttime.emit("admission", rid=_rid_of(req), mode="join",
                           depth=state.depth, prompt_len=plen_eff)
        plen = resume.plen if resume is not None else plen_eff
        t0 = resume.t0 if resume is not None else time.monotonic()
        p0 = time.perf_counter()
        if req.trace is not None:
            if resume is not None:
                req.trace.add_span("preempted", resume.preempt_t, p0,
                                   scheduler="iter")
            else:
                req.trace.add_span("queue_wait", req.t_submit, p0,
                                   scheduler="iter")
        if self.prefix is not None and resume is None:
            # admission prefill through the prefix store: a joiner whose
            # prompt shares a cached prefix forwards only its suffix (and
            # warms the store for the next one). The store's cache is
            # right-aligned — content at [0, plen), no pad — so the merge
            # roll below uses sp = plen. Byte-exact: store replay equals
            # a cold prefill (pinned by tests/test_prefix_cache.py).
            # prefill_state records this row's prefill span (with prefix
            # hit/miss annotations) into the ambient trace.
            with tracing.use_trace(req.trace):
                logits, solo, sp = self.prefix.prefill_state(stream)
        else:
            sp = min(_round_up(plen_eff, self.prompt_bucket), state.depth)
            if sp < plen_eff:  # bucket would overshoot current depth:
                sp = plen_eff  # exact length (rare; one extra program)
            ids = np.zeros((1, sp), dtype=np.int32)
            ids[0, sp - plen_eff:] = stream
            with grafttime.correlate([_rid_of(req)]):
                logits, solo = eng._prefill(
                    eng._run_params(), jnp.asarray(ids),
                    jnp.asarray([sp - plen_eff], jnp.int32))
            if req.trace is not None:
                req.trace.add_span(
                    "prefill", p0, time.perf_counter(),
                    kind="resume" if resume is not None else "admit",
                    depth=state.depth, prompt_len=plen_eff)
        sampling = state.sampling
        if sampling.mode == "greedy":
            first = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]
            dk = None
        else:
            pk, dk = jax.random.split(jnp.asarray(req.key))
            first = select_token(logits, sampling, pk[None, :])[0]
        if resume is not None:
            # the live token is the parked row's last emitted one —
            # known, never re-selected (see _seed_batch)
            first = jnp.asarray(int(resume.tokens[-1]), jnp.int32)
        if self.pool is not None:
            blk_lo, blk_ids = self._place_admitted(
                state, slot, solo, state.depth - sp, reserved)
        else:
            state.cache = _admit_cache(
                state.cache, solo, jnp.asarray(slot, jnp.int32),
                jnp.asarray(state.depth - sp, jnp.int32))
            graftmem.update(state.mem_cache, state.cache)
        state.pad_j = state.pad_j.at[slot].set(state.depth - plen_eff)
        state.token = state.token.at[slot].set(first)
        if state.spec_mode:
            # splice the joiner's stream into its buffer lane: forwarded
            # tokens at [depth - plen_eff, depth), live token at depth —
            # the verify invariant every live row already satisfies.
            # Host-built row + traced-offset writes: no program minted
            # per depth.
            rowbuf = np.zeros((state.buf.shape[1],), np.int32)
            rowbuf[state.depth - plen_eff:state.depth] = stream
            row_j = jax.lax.dynamic_update_slice(
                jnp.asarray(rowbuf), first[None],
                (jnp.asarray(state.depth, jnp.int32),))
            state.buf = state.buf.at[slot].set(row_j)
            if sampling.mode != "greedy":
                # the row's verify key chain starts at its own
                # split(key)[1] (a fresh joiner) or resumes the parked
                # snapshot (the chain advanced with every verify step)
                chain = (jnp.asarray(resume.spec_key)
                         if resume is not None and resume.spec_key
                         is not None else dk)
                state.keys = state.keys.at[slot].set(chain)
        self._order += 1
        state.slots[slot] = _Slot(
            req=req, plen=plen, row=slot,
            first_ref=None if resume is not None else _SegOut(first[None]),
            first_idx=0, dk=dk, t0=t0,
            emitted=resume.emitted if resume is not None else 1,
            resumed_prefix=resume.tokens if resume is not None else None,
            order=resume.order if resume is not None else self._order,
            fault_budget_used=(resume.fault_budget_used
                               if resume is not None else 0))
        if self.pool is not None:
            state.slots[slot].blk_lo = blk_lo
            state.slots[slot].blk_ids = blk_ids
        with self._stats_lock:
            if resume is not None:
                self.resumes += 1
            else:
                self.joins += 1
        if resume is not None:
            REGISTRY.inc("kv_pool_resumes_total")
        else:
            REGISTRY.inc("iter_joins_total")
        if req.max_new_tokens <= (resume.emitted if resume is not None
                                  else 1):
            self._retire_finished(state)

    # -- paged storage (pool mode) -------------------------------------------

    def _init_tables(self, state: _BatchState) -> None:
        """Seed-time placement: allocate each live row's content blocks
        (pad-prefix positions stay on trash), scatter the seed prefill
        into them, and drop the contiguous cache — between segments the
        POOL is the only storage."""
        bs = self.pool.block_size
        state.tables = np.full((len(state.slots), self.pool.nbm),
                               self.pool.trash, dtype=np.int32)
        p_hi = -(-state.depth // bs)
        pad_np = np.asarray(state.pad_j)
        try:
            for i, s in enumerate(state.slots):
                if s is None:
                    continue
                p_lo = int(pad_np[i]) // bs
                s.blk_lo = p_lo
                s.blk_ids = self.pool.allocator.alloc(p_hi - p_lo)
                state.tables[i, p_lo:p_hi] = s.blk_ids
            self.pool.scatter(state.cache, state.tables)
        except BaseException:
            # all-or-nothing: rows placed before the failure must not
            # leak their refs (the seed delivers the error to every
            # request; nothing will ever retire these slots)
            for i in range(len(state.slots)):
                self._release_blocks(state, i)
            raise
        state.cache = None
        # the pool now owns the KV bytes (its own ledger entry); the
        # contiguous working view is gone
        graftmem.release(state.mem_cache)
        state.mem_cache = 0

    def _place_admitted(self, state: _BatchState, slot: int,
                        solo, roll: int,
                        reserved: Tuple[int, List[int]]):
        """Admission-time placement of one solo-prefilled row into its
        PRE-RESERVED content blocks (the atomic ``_reserve_blocks``
        grant — allocation no longer happens here, so the watermark
        check and the grant cannot be split by a concurrent pool user)
        and scatter of the rolled row (the paged form of
        ``_admit_cache``'s roll merge). ``_admit_one`` owns freeing the
        reservation on failure; this only resets the table row."""
        p_lo, ids = reserved
        try:
            state.tables[slot, :] = self.pool.trash
            state.tables[slot, p_lo:p_lo + len(ids)] = ids
            self.pool.scatter_row(solo, state.tables[slot], roll)
        except BaseException:
            state.tables[slot, :] = self.pool.trash
            raise
        return p_lo, ids

    def _release_blocks(self, state: _BatchState, i: int) -> None:
        s = state.slots[i]
        if self.pool is None or s is None or not s.blk_ids:
            return
        self.pool.allocator.free(s.blk_ids)
        s.blk_ids = []
        if state.tables is not None:
            state.tables[i, :] = self.pool.trash

    def _ensure_blocks(self, state: _BatchState, new_depth: int) -> None:
        """Pre-segment growth: every live row must own blocks covering
        depth ``new_depth - 1``'s writes. Walked oldest-first so that
        when allocation fails — even after the allocator LRU-evicted
        every zero-ref prefix entry — the rows preempted to make room
        are the youngest (lowest priority)."""
        from .kv_pool import PoolExhausted
        p_hi = -(-new_depth // self.pool.block_size)
        for s in sorted((s for s in state.slots if s is not None),
                        key=lambda s: s.order):
            if state.slots[s.row] is not s:
                continue  # preempted by an earlier iteration
            while True:
                missing = p_hi - (s.blk_lo + len(s.blk_ids))
                if missing <= 0:
                    break
                try:
                    ids = self.pool.allocator.alloc(missing)
                except PoolExhausted:
                    if not self._preempt_lowest(state):
                        raise  # nothing left to preempt: cannot happen
                        # while the pool holds >= blocks_per_row blocks
                    if state.slots[s.row] is not s:
                        break  # this row WAS the youngest: it parked
                    continue
                col = s.blk_lo + len(s.blk_ids)
                state.tables[s.row, col:p_hi] = ids
                s.blk_ids.extend(ids)

    def _extend_blocks_down(self, state: _BatchState,
                            pad_np: np.ndarray) -> None:
        """Spec-mode low growth: a re-sync roll that shrank a row's pad
        moved real content into columns below ``blk_lo`` — own them
        before the full-row scatter (preempting younger rows if the
        allocator cannot stretch)."""
        from .kv_pool import PoolExhausted
        bs = self.pool.block_size
        for s in sorted((s for s in state.slots if s is not None),
                        key=lambda s: s.order):
            if state.slots[s.row] is not s:
                continue
            new_lo = int(pad_np[s.row]) // bs
            while new_lo < s.blk_lo:
                try:
                    ids = self.pool.allocator.alloc(s.blk_lo - new_lo)
                except PoolExhausted:
                    if not self._preempt_lowest(state):
                        raise
                    if state.slots[s.row] is not s:
                        break
                    continue
                state.tables[s.row, new_lo:s.blk_lo] = ids
                s.blk_ids = ids + s.blk_ids
                s.blk_lo = new_lo

    def _park_slot(self, state: _BatchState, s: _Slot,
                   fault_budget_used: int = 0,
                   reason: str = "preempt") -> None:
        """Park one live row for recompute-resume: fetch its emitted
        tokens (host sync — parking is the slow path by design), free
        its blocks, queue it oldest-first. Shared by pool-pressure
        preemption (``reason="preempt"``) and transient-fault recovery
        (``reason="fault"``) — both replay the row byte-identically
        through the same resume machinery."""
        tokens = np.asarray(self._row_tokens(s), dtype=np.int32)
        spec_key = None
        if state.spec_mode and state.sampling.mode != "greedy":
            spec_key = np.asarray(state.keys[s.row])
        parked = _Parked(req=s.req, plen=s.plen,
                         emitted=min(s.emitted, s.req.max_new_tokens),
                         tokens=tokens, order=s.order, t0=s.t0,
                         preempt_t=time.perf_counter(),
                         spec_key=spec_key,
                         fault_budget_used=fault_budget_used)
        self._release_blocks(state, s.row)
        state.slots[s.row] = None
        self._park(parked)
        grafttime.emit("park", rid=_rid_of(s.req), reason=reason,
                       emitted=parked.emitted)

    def _preempt_lowest(self, state: _BatchState) -> bool:
        """Park the lowest-priority live row (latest admission order).
        The victim set is EVERY live row, including the one whose
        growth triggered the call — priority alone decides (the growth
        loops detect their own row parking and stop)."""
        live = [s for s in state.slots if s is not None]
        if not live:
            return False
        victim = max(live, key=lambda s: s.order)
        grafttime.emit("preempt", rid=_rid_of(victim.req),
                       order=victim.order)
        self._park_slot(state, victim,
                        fault_budget_used=victim.fault_budget_used)
        if victim.req.trace is not None:
            victim.req.trace.labels["preempted"] = (
                victim.req.trace.labels.get("preempted", 0) + 1)
        with self._stats_lock:
            self.preemptions += 1
        REGISTRY.inc("kv_pool_preemptions_total")
        return True

    def _fault_park_all(self, state: _BatchState,
                        fault: Exception) -> None:
        """Transient-fault recovery (graftfault): park EVERY live row —
        the failed segment never appended its output, so each row's
        park snapshot is exactly its pre-segment state and the
        recompute-resume replay is byte-identical. A row past its
        FAULT_PARK_BUDGET fails typed (503 Retry-After upstream)
        instead of cycling park/resume forever."""
        for i, s in enumerate(state.slots):
            if s is None:
                continue
            if s.fault_budget_used + 1 > FAULT_PARK_BUDGET:
                if s.req.trace is not None:
                    t = time.perf_counter()
                    s.req.trace.add_span("fault_budget_exhausted", t, t,
                                         scheduler="iter",
                                         parks=s.fault_budget_used)
                # the row's park-budget breaker OPENS: no more recovery
                # attempts — the degraded-mode decision, on the timeline
                grafttime.emit("breaker", state="open",
                               rid=_rid_of(s.req),
                               scope="iterbatch.fault_park_budget",
                               used=s.fault_budget_used,
                               budget=FAULT_PARK_BUDGET)
                s.req.fail(graftfault.FaultBudgetError(
                    f"row exhausted its transient-fault park budget "
                    f"({FAULT_PARK_BUDGET}); last fault: {fault}"))
                self._release_blocks(state, i)
                state.slots[i] = None
                continue
            if s.req.trace is not None:
                s.req.trace.labels["fault_parks"] = (
                    s.req.trace.labels.get("fault_parks", 0) + 1)
            # budget still absorbs this fault: the breaker stays CLOSED
            # with its remaining headroom recorded
            grafttime.emit("breaker", state="closed",
                           rid=_rid_of(s.req),
                           scope="iterbatch.fault_park_budget",
                           used=s.fault_budget_used + 1,
                           budget=FAULT_PARK_BUDGET)
            self._park_slot(state, s, reason="fault",
                            fault_budget_used=s.fault_budget_used + 1)
        with self._stats_lock:
            self.fault_parks += 1
        REGISTRY.inc("iter_fault_parks_total")

    # -- the segment step ----------------------------------------------------

    def _set_gauges(self, state: _BatchState) -> None:
        """Live-state gauges, refreshed at every scheduling decision
        point (seed, segment boundary): what the batch looks like NOW."""
        live = sum(1 for s in state.slots if s is not None)
        width = len(state.slots)
        occupancy = round(live / max(width, 1), 4)
        depth = self._queue.qsize()
        REGISTRY.gauge("iter_live_rows", live)
        REGISTRY.gauge("batch_occupancy", occupancy, scheduler="iter")
        if self.pool is not None:
            # exact allocator numbers (live rows + prefix entries)
            self.pool.note_gauges(component="iter")
        else:
            kv_block_gauges("iter", state.depth * live,
                            width * self.engine._cache_seq)
        REGISTRY.gauge("queue_depth", depth, scheduler="iter")
        # graftscope occupancy time series: the trajectory behind the
        # instantaneous gauges above, served at /debug/profile
        graftscope.sample("iter_live_rows", live)
        graftscope.sample("batch_occupancy", occupancy, scheduler="iter")
        graftscope.sample("queue_depth", depth, scheduler="iter")

    def _advance(self, state: _BatchState):
        # Seeded mid-decode engine faults (graftfault), fired BEFORE any
        # state mutation so a transient park snapshots exactly the
        # pre-segment state: transient -> park/resume (byte-identical),
        # permanent -> the batch fails typed with partial traces
        # flight-recorded, slow -> a deterministic stall (what drives
        # the deadline-exceeded fixtures).
        kind = graftfault.inject("iterbatch.decode_seg",
                                 "decode_transient", "decode_permanent",
                                 "decode_slow")
        if kind == "decode_slow":
            time.sleep(0.05)
        elif kind == "decode_transient":
            raise graftfault.TransientFault(
                "iterbatch.decode_seg", kind,
                "graftfault: injected transient decode fault")
        elif kind == "decode_permanent":
            raise graftfault.PermanentFault(
                "iterbatch.decode_seg", kind,
                "graftfault: injected permanent engine fault")
        if state.spec_mode:
            return self._advance_spec(state)
        eng = self.engine
        d = state.depth
        n = min(self.seg_steps, eng.max_seq - d)
        assert n >= 1, "active rows past max_seq (admission bug)"
        window = eng._decode_window(d + n)   # shared bucket policy
        pooled = self.pool is not None
        if pooled:
            # grow every live row's block range to cover this segment's
            # writes — THE preemption point (youngest row parks when
            # even LRU eviction cannot free enough blocks)
            self._ensure_blocks(state, d + n)
            if not state.active():
                return  # everyone preempted (single-row pool squeeze)
            cache = self.pool.gather(state.tables, d)
        else:
            cache = state.cache
        step_keys = self._segment_keys(state, n)
        t0 = time.perf_counter()
        out, cache = eng._decode_seg(
            eng._run_params(), state.token, cache, state.pad_j,
            step_keys, sampling=state.sampling, window=window)
        if pooled:
            self.pool.scatter(cache, state.tables)
            self.pool.note_compiles()
        else:
            state.cache = cache
        state.token = out[:, -1]
        state.depth = d + n
        seg = _SegOut(out)
        t1 = time.perf_counter()
        eng._note_compiles()
        # per-decode-step time, serving-thread DISPATCH view: segments
        # queue asynchronously on the device, so this is enqueue cost,
        # not device truth (the engine-component series is; see
        # utils.metrics METRIC_CATALOG)
        REGISTRY.observe("decode_step_seconds", (t1 - t0) / n,
                         component="iter")
        with self._stats_lock:
            self.segments_run += 1
        REGISTRY.inc("iter_segments_total")
        for s in state.slots:
            if s is not None:
                s.segs.append((seg, n))
                s.emitted += n
                if s.req.trace is not None:
                    # dispatch wall time (segments queue asynchronously
                    # on the device — the serving-thread view)
                    s.req.trace.add_span(
                        "decode", t0, t1, seg=True, steps=n,
                        width=len(state.slots), depth=state.depth,
                        step_ms=round((t1 - t0) / n * 1e3, 3),
                        **({"blocks": len(s.blk_ids)} if pooled else {}))
        self._retire_finished(state)
        self._set_gauges(state)

    def _advance_spec(self, state: _BatchState):
        """One draft-verify SEGMENT (spec batches): up to
        ``seg_steps // (draft_len + 1)`` verify forwards — the same
        device-work quantum as ``seg_steps`` single-token steps — with
        per-row acceptance, rewind, and uniform-depth re-sync all inside
        ONE compiled program (spec_decode._seg_b). Each row's emission
        is capped at its own remaining budget, so a short row never
        over-decodes and ghost rows (budget 0) stay inert.

        Costs ONE host sync per segment: the scheduler must read the
        per-row emission counts, the new per-row pads, and the new
        uniform depth to retire/admit (the price of data-dependent
        progress — same class as EOS-armed batches); the token buffer's
        device->host copy rides the same window and MUST materialize
        here, before the next segment donates the buffer."""
        eng = self.engine
        K = self.spec.draft_len
        max_verify = max(1, self.seg_steps // (K + 1))
        pooled = self.pool is not None
        if pooled:
            # verify headroom: writes reach depth + K within a verify,
            # and the segment can emit up to max_verify * (K + 1) new
            # tokens — cover the worst case before dispatch (preempting
            # youngest rows if the allocator cannot stretch)
            worst = min(state.depth + max_verify * (K + 1) + K,
                        eng.max_seq)
            self._ensure_blocks(state, worst)
            if not state.active():
                return
            in_cache = self.pool.gather(state.tables, state.depth)
        else:
            in_cache = state.cache
        # budgets AFTER any preemption above: a row parked at this
        # boundary must enter the segment as an inert ghost (budget 0),
        # not keep drafting into the trash block
        b = len(state.slots)
        budgets = np.zeros((b,), np.int32)
        for i, s in enumerate(state.slots):
            if s is not None:
                budgets[i] = max(s.req.max_new_tokens - s.emitted, 0)
        t0 = time.perf_counter()
        # the spec flag is routing metadata: normalize it out of the
        # static sampling arg so the segment program is shared with (and
        # byte-identical to) the solo spec engine's acceptance math
        sampling = dataclasses.replace(state.sampling, spec=False)
        buf, total, cache, pad, emitted, steps, keys = self.spec._seg_b(
            eng._run_params(), state.buf, in_cache,
            jnp.asarray(state.depth + 1, jnp.int32), state.pad_j,
            state.keys, jnp.asarray(budgets),
            max_verify=max_verify, sampling=sampling)
        state.buf = buf
        state.pad_j, state.keys = pad, keys
        seg = _SegOut(buf)
        emitted_np = np.asarray(emitted)          # THE per-segment sync
        pad_np = np.asarray(pad)
        steps_i = int(steps)
        state.depth = int(total) - 1
        # slot progress updates FIRST: a preemption triggered by the
        # pool handoff below must park a POST-segment-consistent
        # snapshot (emitted, buffer, key chain all advanced together)
        for s in state.slots:
            if s is not None:
                s.emitted += int(emitted_np[s.row])
                s.spec_buf = seg
                s.spec_pad = int(pad_np[s.row])
        if pooled:
            # The spec segment's per-row rewind/re-sync ROLLS whole
            # cache rows (spec_decode._roll_cache_rows — a permutation
            # of every slot, not an append), so (a) a row's content can
            # extend DOWNWARD into what used to be pad — any table
            # column the roll made live must own a real block before
            # the handoff, or the scatter would drop content into the
            # trash block — and (b) the handoff must rewrite the full
            # row, never just the new columns. The declared contract
            # keeps the two modules honest.
            from .spec_decode import SEG_REWRITES_FULL_CACHE
            assert SEG_REWRITES_FULL_CACHE, (
                "spec segments no longer rewrite whole cache rows; the "
                "pool handoff can narrow to the new columns")
            self._extend_blocks_down(state, pad_np)
            self.pool.scatter(cache, state.tables)
            self.pool.note_compiles()
        else:
            state.cache = cache
        _ = seg.np  # materialize: the next segment donates ``buf``
        with self._stats_lock:
            self.segments_run += 1
            self.spec_segments_run += 1
        # acceptance stats flow through the spec engine's one accounting
        # path (counters + /healthz stats + the acceptance-rate gauge),
        # so solo-spec and spec x iterbatch modes cannot diverge;
        # requests are counted at retirement (_deliver), hence 0 here
        self.spec._update_stats(0, int(emitted_np.sum()), steps_i)
        REGISTRY.inc("iter_segments_total")
        REGISTRY.inc("iter_spec_segments_total")
        self.spec._note_compiles()
        t1 = time.perf_counter()
        # per-VERIFY-step time (a spec segment's scheduling quantum);
        # this window includes the segment's one documented host sync,
        # so it is closer to device truth than the plain-segment view
        REGISTRY.observe("decode_step_seconds",
                         (t1 - t0) / max(steps_i, 1),
                         component="iter_spec")
        for s in state.slots:
            if s is not None and s.req.trace is not None:
                s.req.trace.add_span(
                    "decode", t0, t1, seg=True, spec=True,
                    verify_steps=steps_i,
                    emitted=int(emitted_np[s.row]),
                    width=len(state.slots), depth=state.depth,
                    **({"blocks": len(s.blk_ids)} if pooled else {}))
        self._retire_finished(state)
        self._set_gauges(state)

    def _segment_keys(self, state: _BatchState, n: int):
        """[n, B, 2] per-step keys. Sample rows consume THEIR OWN step
        indices (emitted-1 ... emitted-1+n of split(dk, .) — prefix-
        stable, so a late joiner's stream matches its solo run); greedy
        segments pass zeros (the program's key operand is never read)."""
        b = len(state.slots)
        if state.sampling.mode == "greedy":
            return jnp.zeros((n, b, 2), jnp.uint32)
        cols = []
        for s in state.slots:
            if s is None or s.dk is None:
                cols.append(jnp.zeros((n, 2), jnp.uint32))
            else:
                t0 = s.emitted - 1
                cols.append(jax.random.split(s.dk, t0 + n)[t0:])
        return jnp.stack(cols, axis=1)              # [n, B, 2]

    # -- retirement ----------------------------------------------------------

    def _retire_finished(self, state: _BatchState):
        eos_armed = any(s is not None and s.req.eos_id is not None
                        for s in state.slots)
        for i, s in enumerate(state.slots):
            if s is None:
                continue
            if (s.req.deadline is not None and s.req.deadline.expired()
                    and not s.req.done.is_set()):
                # Past-deadline row: cancelled at THIS segment boundary
                # with its blocks freed (GRAFTSAN conservation holds
                # through it) and a typed failure delivered — the
                # deadline budget is honored mid-decode, not only at
                # admission.
                if s.req.trace is not None:
                    t = time.perf_counter()
                    s.req.trace.add_span("deadline_exceeded", t, t,
                                         scheduler="iter",
                                         emitted=s.emitted)
                s.req.fail(graftfault.DeadlineExceeded(
                    "deadline budget exhausted mid-decode; row "
                    "cancelled at the segment boundary"))
                s.req.cancelled.set()
                self._release_blocks(state, i)
                state.slots[i] = None
                continue
            if s.req.cancelled.is_set():
                # Caller timed out and left: free the slot instead of
                # decoding dead tokens for nobody. Nothing is delivered
                # (the payload has no reader). The flight recorder gets
                # an ``abandoned`` span at the moment the blocks come
                # back, so the reclamation is observable, not implicit.
                if s.req.trace is not None:
                    t = time.perf_counter()
                    s.req.trace.add_span("abandoned", t, t,
                                         scheduler="iter",
                                         emitted=s.emitted)
                self._release_blocks(state, i)
                state.slots[i] = None
                continue
            done = s.emitted >= s.req.max_new_tokens
            eos_at = None
            if s.req.eos_id is not None and (done or eos_armed):
                # EOS scan forces the segment fetch; only armed batches
                # pay this per-segment sync
                toks = self._row_tokens(s)
                hits = np.flatnonzero(toks == s.req.eos_id)
                if hits.size:
                    eos_at = int(hits[0])
                    done = True
            if done:
                self._deliver(state, i, s, eos_at)

    def _row_tokens(self, s: _Slot) -> np.ndarray:
        if s.spec_buf is not None:
            # spec rows: the buffer IS the stream — prompt at
            # [pad, pad + plen), everything emitted right after it
            # (resumed rows included: the resume splice rebuilt the
            # lane with the full emitted stream in place)
            row = s.spec_buf.np[s.row]
            start = s.spec_pad + s.plen
            n = min(s.emitted, s.req.max_new_tokens)
            return row[start:start + n]
        if s.resumed_prefix is not None:
            # a resumed row's pre-preemption tokens were fetched at the
            # park; segments since the resume append after them
            parts = [s.resumed_prefix]
        else:
            parts = [s.first_ref.np[s.first_idx:s.first_idx + 1]]
        parts += [seg.np[s.row] for seg, _ in s.segs]
        return np.concatenate(parts)[:s.req.max_new_tokens]

    def _deliver(self, state: _BatchState, i: int, s: _Slot, eos_at):
        """Retire the slot and hand the row to its caller. No fetch
        happens here — the caller's thread assembles the tokens (see
        ``generate``), so the scheduler keeps dispatching."""
        if eos_at is not None and eos_at + 1 < s.req.max_new_tokens:
            with self._stats_lock:
                self.eos_retires += 1
            REGISTRY.inc("iter_eos_retires_total")
        s.done_t = time.monotonic()
        s.req.payload = (s, eos_at)
        s.req.done.set()
        self._release_blocks(state, i)
        state.slots[i] = None
        with self._stats_lock:
            self.rows_served += 1
        if state.spec_mode:
            with self.spec._stats_lock:
                self.spec._requests += 1
        REGISTRY.inc("iter_rows_total")
