"""Iteration-level continuous batching: join/retire at segment boundaries.

``runtime.batcher`` batches at ADMISSION: it groups waiting requests,
runs one bucketed decode to completion, and only then looks at the queue
again — a request arriving mid-decode waits out the whole batch
(VERDICT r3 weak #3). This module schedules at ITERATION level, the
vLLM-style upgrade: the decode runs as fixed-size compiled segments, and
between segments the scheduler

- **admits** queued requests into free batch slots (solo bucketed
  prefill, then the row's K/V merges into the live cache at the current
  depth — the same roll-and-mask move the prefix batcher uses), and
- **retires** rows that finished (their ``max_new_tokens`` reached, or
  their ``eos_id`` emitted — early-EOS rows free their slot instead of
  decoding dead tokens to the end of the batch).

The segment loop dispatches asynchronously: segments queue back-to-back
on the device with NO host sync unless a decision is needed (a retiring
row's tokens are fetched for delivery; EOS-armed rows force a fetch per
segment). The device never idles waiting for the host on the fast path.

Exactness is the same bar as the admission batcher, per row:

- greedy rows equal their solo engine runs token-for-token (row-
  independent attention + left-pad masking — a joined row's cache
  content at slots ``[d - plen, d)`` with ``pad = d - plen`` is exactly
  a solo run's, shifted);
- seeded sample rows are byte-equal to solo runs: per-row keys with the
  row's OWN step offsets (``split(dk, n)[t]`` is prefix-stable, so a
  row joining at depth d still consumes key ``t`` at its step ``t``).

Batches are policy-pure (one SamplingConfig per live batch, like the
admission batcher); an incompatible arrival closes admission and seeds
the next batch, preserving FIFO. MoE is refused: its routing is not
window-independent (``models.is_window_independent``), so a row's
tokens could depend on batch composition.

Batches are RIGHT-SIZED (ADVICE r4): a batch compiles at the smallest
power-of-two width that fits its seed and grows on demand when an
arrival finds no free slot — a lone request decodes at width 1 instead
of paying ``max_batch`` x ghost-row FLOPs. Ghost rows (width minus live
rows) replicate a real row; per-row independence keeps them inert.

Compiled-program inventory (bounded): the engine's prefill programs
(prompt-bucketed), ONE decode-segment program per (window bucket,
sampling, power-of-two batch width up to ``max_batch``) and segment
length (plus cache-tail remainders, quantized by construction), one
admit program per width, and one tiny grow program per adjacent width
pair.

Speculative segments (``spec=``): a batch whose policy carries the
``SamplingConfig.spec`` flag advances through the speculative engine's
draft-verify SEGMENT program (runtime.spec_decode.``_seg_b``) instead of
the single-token segment scan: each segment runs up to
``seg_steps // (draft_len + 1)`` verify forwards, every row accepting
its own ``k_i in [0, draft_len]`` drafts per verify with a per-row
cache rewind (uniform-depth re-sync — rows stay mergeable, so admission
and retirement keep working mid-speculation). Per-row emission within a
segment is ragged, so a spec segment costs ONE host sync (fetching
per-row counts + the new depth) — the price of data-dependent progress,
same class as EOS-armed batches. Exactness bar unchanged: every row —
seeded sample rows included — is byte-equal to its solo
``SpecDecodeEngine.generate`` run (per-row key chains resume across
segments; joiners start their chain at their own step 0). Spec batches
admit only rows speculation is exact for (prompt >= ngram, draft_len
slots of headroom); the ``spec`` flag is part of policy equality, so a
spec arrival during a plain batch (or vice versa) closes admission and
seeds the next batch — the same FIFO-preserving policy-change handling
as any sampling change. One spec-segment program per (width, policy):
acceptance counts are traced, never program keys.

Prefix-cache composition (``prefix=``): admissions prefill through the
prefix store (``PrefixCachingEngine.prefill_state``) — a joiner whose
prompt shares a cached prefix forwards only its suffix before merging
into the live batch at the current depth. Exact (store replay is
byte-identical to a cold prefill) and compile-bounded by the store's
chunk programs.
"""

from __future__ import annotations

import dataclasses
import functools
import queue
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.attention import KVCache
from ..utils import tracing
from ..utils.metrics import REGISTRY
from .batcher import _round_up
from .engine import (DecodeEngine, GenerateResult, SamplingConfig,
                     select_token)


# Static-analysis contract (tools/graftcheck): every ``jax.jit`` site in
# this module, by holding name — enumerated by the recompile-budget
# certifier; an undeclared site is a lint finding.
JIT_ENTRY_POINTS = ("_admit_cache",)

# Decode hot-loop scopes (tools/graftcheck host-sync rule): the segment
# dispatch loop is the zero-sync fast path; the spec variant's syncs are
# the documented per-segment price and are baselined.
GRAFTCHECK_HOT_LOOPS = ("IterBatchingEngine._advance",
                        "IterBatchingEngine._advance_spec")


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@dataclasses.dataclass
class _Req:
    prompt: np.ndarray
    max_new_tokens: int
    sampling: SamplingConfig
    key: Optional[jax.Array]
    eos_id: Optional[int]
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    payload: Optional[tuple] = None   # (_Slot, eos_at) — caller assembles
    error: Optional[Exception] = None
    # Set by generate() on timeout: the caller is gone, so the scheduler
    # drops the request at dequeue and frees its slot at the next
    # retirement pass instead of decoding dead tokens for nobody.
    cancelled: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    # request-trace propagation (caller's ambient RequestTrace): the
    # scheduler stamps queue wait, the admission prefill, and every
    # decode segment the row rode into it
    trace: Optional[object] = None
    t_submit: float = 0.0

    def fail(self, e: Exception) -> None:
        """Deliver an error exactly once (idempotent across the several
        except paths that may observe the same request)."""
        if not self.done.is_set():
            self.error = e
            self.done.set()


class _SegOut:
    """One segment's [B, n] token output, fetched to host at most once
    (several retiring rows may share it; caller threads race the fetch,
    hence the lock). The device->host copy starts ASYNC at construction
    so it overlaps later segments — by delivery time it is usually
    already resident."""

    def __init__(self, arr):
        self.arr = arr
        self._np = None
        self._lock = threading.Lock()
        try:
            arr.copy_to_host_async()
        except AttributeError:  # non-jax array (tests)
            pass

    @property
    def np(self) -> np.ndarray:
        with self._lock:
            if self._np is None:
                self._np = np.asarray(self.arr)
            return self._np


@dataclasses.dataclass
class _Slot:
    req: _Req
    plen: int
    row: int                      # this slot's batch row index (fixed)
    first_ref: "_SegOut"          # holds the first generated token ...
    first_idx: int                # ... at this index
    dk: Optional[jax.Array]       # per-row decode key (sample mode)
    emitted: int = 1              # tokens generated so far (incl. first)
    segs: List = dataclasses.field(default_factory=list)  # (_SegOut, n)
    # Spec-mode delivery state: the latest segment's [B, buflen] token
    # buffer (prompt + everything emitted, per row, left-aligned at the
    # row's pad) and this row's pad at that moment — _row_tokens reads
    # the stream straight out of it, no per-segment part list needed.
    spec_buf: Optional["_SegOut"] = None
    spec_pad: int = 0
    t0: float = 0.0
    done_t: float = 0.0


@functools.partial(jax.jit, donate_argnums=(0,))
def _admit_cache(cache, solo, slot, roll):
    """Merge a solo-prefilled row into batch slot ``slot``: the row's
    K/V content rolls from solo slots ``[sp - plen, sp)`` to the batch's
    ``[d - plen, d)`` (``roll = d - sp``; wrap garbage lands in the
    masked pad prefix or in not-yet-written slots that decode overwrites
    before reading). ``slot``/``roll`` are traced scalars — one compiled
    program serves every admission. Handles plain, fused (placeholder
    ``v``), and staged (list) cache forms."""
    def one(c: KVCache, s: KVCache) -> KVCache:
        k = jax.lax.dynamic_update_slice_in_dim(
            c.k, jnp.roll(s.k, roll, axis=-2), slot, axis=1)
        if getattr(c.v, "ndim", 0) <= 1:      # fused cache: v placeholder
            v = c.v
        else:
            v = jax.lax.dynamic_update_slice_in_dim(
                c.v, jnp.roll(s.v, roll, axis=-2), slot, axis=1)
        return KVCache(k=k, v=v, length=c.length)

    if isinstance(cache, list):
        return [one(c, s) for c, s in zip(cache, solo)]
    return one(cache, solo)


class _BatchState:
    """The live batch between segments (worker-thread-only state)."""

    def __init__(self, sampling, token, cache, pad_j, depth):
        self.sampling = sampling
        self.token = token            # [B] device
        self.cache = cache
        self.pad_j = pad_j            # [B] device int32
        self.depth = depth            # uniform cache depth (host int)
        self.slots: List[Optional[_Slot]] = []
        self.closed = False           # True: no more admissions (FIFO)
        # speculative batches only: device token buffer [B, buflen]
        # (prompt + emitted per row, content ending at depth + 1) and
        # the per-row verify key chains [B, 2] (sample mode)
        self.spec_mode = False
        self.buf = None
        self.keys = None

    def active(self):
        return any(s is not None for s in self.slots)


class IterBatchingEngine:
    """Thread-safe iteration-level batching front end over a
    ``DecodeEngine`` (same calling convention as ``BatchingEngine``).

    ``seg_steps`` is the scheduling granularity: admissions and
    retirements happen every ``seg_steps`` decode steps. Smaller = lower
    join latency, more scheduler work; larger = better dispatch
    pipelining. A request's worst-case join delay is one segment.
    """

    def __init__(self, engine: DecodeEngine, max_batch: int = 8,
                 seg_steps: int = 32, max_wait_ms: float = 2.0,
                 prompt_bucket: int = 16, spec=None, prefix=None):
        """``spec`` (optional ``SpecDecodeEngine`` wrapping THIS engine)
        enables speculative segments: batches whose policy carries
        ``SamplingConfig.spec`` advance by draft-verify forwards instead
        of single-token steps (see module docstring). ``prefix``
        (optional ``PrefixCachingEngine`` wrapping THIS engine) routes
        admission prefills through the prefix store, so a joiner with a
        warm prefix forwards only its suffix."""
        from ..models import is_window_independent
        if not is_window_independent(engine.config):
            raise NotImplementedError(
                "iteration-level batching requires window-independent "
                "routing (a joined MoE row's tokens could depend on "
                "batch composition); MoE serves via the admission "
                "batcher")
        if engine.prefill_chunk:
            raise NotImplementedError(
                "iteration-level batching prefills admissions solo at "
                "bucketed lengths; it does not compose with "
                "prefill_chunk (use the admission batcher)")
        if engine._mesh is not None:
            raise NotImplementedError(
                "iteration-level batching drives the single-device "
                "engine; mesh decode (tp/ep) uses the admission batcher")
        if spec is not None and spec.plain is not engine:
            raise ValueError("spec must wrap the same DecodeEngine (shared "
                             "weights/programs), got a different instance")
        if prefix is not None and prefix.plain is not engine:
            raise ValueError("prefix must wrap the same engine instance")
        self.engine = engine
        self.spec = spec
        self.prefix = prefix
        self.max_batch = max_batch
        self.seg_steps = seg_steps
        self.max_wait_s = max_wait_ms / 1e3
        self.prompt_bucket = prompt_bucket
        self._queue: "queue.Queue[_Req]" = queue.Queue()
        self._pending: Optional[_Req] = None
        self._stats_lock = threading.Lock()
        self.batches_run = 0
        self.rows_served = 0
        self.joins = 0                # admissions into a LIVE batch
        self.segments_run = 0
        self.spec_segments_run = 0    # draft-verify segments (spec mode)
        self.eos_retires = 0
        self.grows = 0                # width upgrades of a live batch
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    # -- caller side ---------------------------------------------------------

    def generate(self, prompt_ids, max_new_tokens: int,
                 sampling: SamplingConfig = SamplingConfig(),
                 key: Optional[jax.Array] = None,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None) -> GenerateResult:
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must be non-empty")
        if len(prompt) + max_new_tokens > self.engine.max_seq:
            raise ValueError(
                f"prompt_len={len(prompt)} + max_new_tokens="
                f"{max_new_tokens} exceeds max_seq={self.engine.max_seq}")
        if sampling.mode != "greedy" and key is None:
            raise ValueError(
                "sample-mode requests must carry a per-request PRNG key")
        if sampling.spec:
            # caller-thread eligibility: a spec-flagged request the
            # verify loop cannot serve exactly must be refused HERE with
            # its own numbers, not discovered mid-batch (rule defined
            # once, on the engine)
            if self.spec is None:
                raise ValueError(
                    "sampling.spec requested but this scheduler has no "
                    "speculative engine attached (pass spec= at "
                    "construction)")
            self.spec.check_request(len(prompt), max_new_tokens)
        req = _Req(prompt=prompt, max_new_tokens=max_new_tokens,
                   sampling=sampling, key=key, eos_id=eos_id,
                   trace=tracing.current_trace(),
                   t_submit=time.perf_counter())
        self._queue.put(req)
        REGISTRY.gauge("queue_depth", self._queue.qsize(),
                       scheduler="iter")
        if not req.done.wait(timeout):
            # Cancel, don't just abandon: the scheduler skips cancelled
            # requests at dequeue and retires a cancelled live row at the
            # next segment boundary, so repeated timeouts cannot
            # accumulate dead decode work (ADVICE r4).
            req.cancelled.set()
            raise TimeoutError("iter-batched generate timed out")
        if req.error is not None:
            raise req.error
        # token assembly (the device->host fetches) happens HERE, on the
        # caller's thread: the scheduler thread only marks rows done, so
        # it never blocks on a transfer and keeps dispatching segments.
        # The async copies started at segment creation usually make this
        # a no-wait read.
        s, eos_at = req.payload
        new = self._row_tokens(s)
        if eos_at is not None:
            new = new[:eos_at + 1]
        tokens = np.concatenate([req.prompt, new])[None, :]
        # Timing caveat: the scheduler never syncs per phase, so
        # decode_seconds here is the row's WALL time from admission to
        # retirement (prefill + shared segments + scheduling), not a
        # pure decode window — an honest end-to-end number, but do not
        # read tokens_per_second as a device decode rate.
        return GenerateResult(
            tokens=tokens, prompt_len=s.plen,
            prefill_seconds=0.0, decode_seconds=s.done_t - s.t0,
            new_tokens=len(new), decode_steps=len(new) - 1)

    def stats(self) -> dict:
        with self._stats_lock:
            return {"batches": self.batches_run, "rows": self.rows_served,
                    "joins": self.joins, "segments": self.segments_run,
                    "spec_segments": self.spec_segments_run,
                    "eos_retires": self.eos_retires, "grows": self.grows}

    # -- worker side ---------------------------------------------------------

    def _loop(self):
        while True:
            head = self._pending or self._queue.get()
            self._pending = None
            if head.cancelled.is_set():
                continue
            try:
                self._run_batch(head)
            except Exception as e:  # noqa: BLE001 — delivered per-request
                head.fail(e)

    def _compatible(self, state: _BatchState, req: _Req) -> bool:
        """Can ``req`` join the live batch right now? Policy must match
        (the ``spec`` flag included — a spec arrival never joins a plain
        batch or vice versa), its prompt must fit the current depth
        (content at ``[d - plen, d)``), and its generation must fit the
        cache — with ``draft_len`` extra slots of verify-write headroom
        when the batch speculates."""
        reserve = self.spec.draft_len if state.spec_mode else 0
        return (req.sampling == state.sampling
                and len(req.prompt) <= state.depth
                and state.depth + req.max_new_tokens + reserve
                <= self.engine.max_seq)

    def _run_batch(self, head: _Req):
        state = self._seed(head)
        try:
            while state.active():
                if not state.closed:
                    self._admit(state)
                self._advance(state)
        except Exception as e:  # noqa: BLE001
            for s in state.slots:
                if s is not None:
                    s.req.fail(e)
            raise

    # -- seeding -------------------------------------------------------------

    def _seed(self, head: _Req) -> _BatchState:
        """Start a batch: gather up-to-``max_wait`` same-policy peers
        that fit together, batched prefill, first tokens.  Any failure
        past the gathering point (e.g. a prefill OOM) is delivered to
        EVERY gathered request, not just the head — a gathered peer with
        ``done`` never set would block its caller forever (ADVICE r4
        medium)."""
        seed = [head]
        deadline = time.monotonic() + self.max_wait_s
        while len(seed) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt.cancelled.is_set():
                continue
            if nxt.sampling == seed[0].sampling and self._fits(seed + [nxt]):
                seed.append(nxt)
            else:
                # incompatible arrival: parked as the FIFO head — _admit
                # reconsiders it first (it may fit once the batch is
                # live) and otherwise it seeds the next batch
                self._pending = nxt
                break
        try:
            return self._seed_batch(seed)
        except Exception as e:  # noqa: BLE001
            for r in seed:
                r.fail(e)
            raise

    def _seed_batch(self, seed: List[_Req]) -> _BatchState:
        eng = self.engine
        spec_mode = seed[0].sampling.spec
        s_max = self._seed_smax(seed)

        # Right-size the compiled width (ADVICE r4: a lone request must
        # not pay max_batch x prefill/decode FLOPs for ghost rows): the
        # batch runs at the next power of two that fits the seed, and
        # _admit grows it on demand. Width set = {1, 2, 4, ..,
        # max_batch} — a bounded extra-program inventory.
        b = min(_next_pow2(len(seed)), self.max_batch)
        ids = np.zeros((b, s_max), dtype=np.int32)
        pad = np.zeros((b,), dtype=np.int32)
        for i in range(b):
            r = seed[min(i, len(seed) - 1)]   # free slots replicate last
            ids[i, s_max - len(r.prompt):] = r.prompt
            pad[i] = s_max - len(r.prompt)
        ids_j = jnp.asarray(ids)
        pad_j = jnp.asarray(pad)

        t0 = time.monotonic()
        sp0 = time.perf_counter()
        run_params = eng._run_params()
        last_logits, cache = eng._prefill(run_params, ids_j, pad_j)
        sampling = seed[0].sampling
        first, pks, dks = self._first_tokens(
            last_logits, sampling, [r.key for r in seed], b)
        sp1 = time.perf_counter()
        for r in seed:
            if r.trace is not None:
                r.trace.add_span("queue_wait", r.t_submit, sp0,
                                 scheduler="iter")
                r.trace.add_span("prefill", sp0, sp1, kind="seed",
                                 width=b, prompt_len=len(r.prompt))

        state = _BatchState(sampling, first, cache, pad_j, s_max)
        if spec_mode:
            # verify-loop entry state (spec_decode._seg_b invariant): the
            # token buffer holds prompt + the unforwarded first token per
            # row, content at [pad_b, depth + 1); the per-row key chains
            # are the dks the solo loop would carry (split(key)[1]).
            buf = jnp.zeros((b, eng.max_seq + self.spec.draft_len + 1),
                            jnp.int32)
            buf = jax.lax.dynamic_update_slice(buf, ids_j, (0, 0))
            buf = jax.lax.dynamic_update_slice(buf, first[:, None],
                                               (0, s_max))
            state.spec_mode = True
            state.buf = buf
            state.keys = (dks if dks is not None
                          else jnp.zeros((b, 2), jnp.uint32))
        first_ref = _SegOut(first)          # one shared [B] fetch
        state.slots = [None] * b
        for i, r in enumerate(seed):
            state.slots[i] = _Slot(req=r, plen=len(r.prompt), row=i,
                                   first_ref=first_ref, first_idx=i,
                                   dk=None if dks is None else dks[i],
                                   t0=t0)
        with self._stats_lock:
            self.batches_run += 1
        REGISTRY.inc("iter_batches_total")
        self.engine._note_compiles()
        self._retire_finished(state)      # max_new_tokens == 1 rows
        self._set_gauges(state)
        return state

    def _fits(self, reqs: List[_Req]) -> bool:
        s_max = self._seed_smax(reqs)
        reserve = self._reserve(reqs[0])
        return all(s_max + r.max_new_tokens + reserve <= self.engine.max_seq
                   and len(r.prompt) <= s_max for r in reqs)

    def _reserve(self, req: _Req) -> int:
        """Cache slots held back beyond the generation: speculative
        batches need ``draft_len`` of verify-write headroom past the
        deepest content slot (the spec engine's own guard, applied to
        the batch's shared shape)."""
        return self.spec.draft_len if req.sampling.spec else 0

    def _seed_smax(self, reqs: List[_Req]) -> int:
        raw = max(len(r.prompt) for r in reqs)
        need = max(r.max_new_tokens for r in reqs)
        return min(_round_up(raw, self.prompt_bucket),
                   self.engine.max_seq - need - self._reserve(reqs[0]))

    def _first_tokens(self, last_logits, sampling, keys, b):
        """First-token selection + per-row (prefill, decode) key split.
        Free slots get zero keys (their draws are dropped)."""
        if sampling.mode == "greedy":
            first = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            return first, None, None
        ks = [jnp.asarray(k) for k in keys]
        ks += [jnp.zeros_like(ks[0])] * (b - len(ks))
        stack = jnp.stack(ks)                       # [b, 2]
        pair = jax.vmap(jax.random.split)(stack)    # [b, 2, 2]
        pks, dks = pair[:, 0], pair[:, 1]
        first = select_token(last_logits, sampling, pks)
        return first, pks, dks

    # -- admission -----------------------------------------------------------

    def _admit(self, state: _BatchState):
        """Drain compatible queued requests into free slots (strict FIFO:
        an incompatible head closes admission for this batch and seeds
        the next one). A request parked in ``_pending`` (by ``_seed`` or
        a previous round) is ALWAYS the head — it is reconsidered first
        and never overwritten, so no request can be dropped.  When the
        right-sized batch has no free slot but is narrower than
        ``max_batch``, the live batch GROWS to the next power of two
        (ghost rows replicate row 0; per-row exactness makes them
        inert) instead of turning the arrival away."""
        while True:
            if self._pending is None:
                try:
                    self._pending = self._queue.get_nowait()
                except queue.Empty:
                    return
            req = self._pending
            if req.cancelled.is_set():
                self._pending = None
                continue
            if not self._compatible(state, req):
                state.closed = True  # req stays parked as the FIFO head
                return
            free = [i for i, s in enumerate(state.slots) if s is None]
            if not free:
                if len(state.slots) >= self.max_batch:
                    return  # full batch: req stays parked, retried at
                    # the next segment boundary (a slot may retire)
                self._grow(state)
                free = [i for i, s in enumerate(state.slots) if s is None]
            self._pending = None
            try:
                self._admit_one(state, req, free[0])
            except Exception as e:  # noqa: BLE001 — the popped request is
                req.fail(e)        # not in state.slots yet; without this
                raise              # its caller would block forever

    def _grow(self, state: _BatchState):
        """Widen the live batch to the next power of two: pad token /
        pad_j / cache along the batch axis by replicating row 0 (any
        live content is valid ghost material — rows are independent).
        One tiny concat program per (width, cache-shape) pair, from the
        same bounded width set as the decode programs."""
        old = len(state.slots)
        new = min(_next_pow2(old + 1), self.max_batch)
        pad_rows = new - old

        def rep(x, axis):
            return jnp.concatenate(
                [x, jnp.repeat(jax.lax.slice_in_dim(x, 0, 1, axis=axis),
                               pad_rows, axis=axis)], axis=axis)

        def grow_cache(c):
            def one(kc: KVCache) -> KVCache:
                v = kc.v if getattr(kc.v, "ndim", 0) <= 1 else rep(kc.v, 1)
                return KVCache(k=rep(kc.k, 1), v=v, length=kc.length)
            if isinstance(c, list):
                return [one(x) for x in c]
            return one(c)

        state.token = rep(state.token, 0)
        state.pad_j = rep(state.pad_j, 0)
        state.cache = grow_cache(state.cache)
        if state.spec_mode:
            # ghost rows clone row 0's buffer/key lane; their zero
            # budgets keep them inert through every verify (n_emit = 0)
            state.buf = rep(state.buf, 0)
            state.keys = rep(state.keys, 0)
        state.slots = state.slots + [None] * pad_rows
        with self._stats_lock:
            self.grows += 1
        REGISTRY.inc("iter_grows_total")

    def _admit_one(self, state: _BatchState, req: _Req, slot: int):
        eng = self.engine
        plen = len(req.prompt)
        t0 = time.monotonic()
        p0 = time.perf_counter()
        if req.trace is not None:
            req.trace.add_span("queue_wait", req.t_submit, p0,
                               scheduler="iter")
        if self.prefix is not None:
            # admission prefill through the prefix store: a joiner whose
            # prompt shares a cached prefix forwards only its suffix (and
            # warms the store for the next one). The store's cache is
            # right-aligned — content at [0, plen), no pad — so the merge
            # roll below uses sp = plen. Byte-exact: store replay equals
            # a cold prefill (pinned by tests/test_prefix_cache.py).
            # prefill_state records this row's prefill span (with prefix
            # hit/miss annotations) into the ambient trace.
            with tracing.use_trace(req.trace):
                logits, solo, sp = self.prefix.prefill_state(req.prompt)
        else:
            sp = min(_round_up(plen, self.prompt_bucket), state.depth)
            if sp < plen:   # bucket would overshoot current depth: exact
                sp = plen   # length (rare; costs one extra prefill program)
            ids = np.zeros((1, sp), dtype=np.int32)
            ids[0, sp - plen:] = req.prompt
            logits, solo = eng._prefill(eng._run_params(),
                                        jnp.asarray(ids),
                                        jnp.asarray([sp - plen], jnp.int32))
            if req.trace is not None:
                req.trace.add_span("prefill", p0, time.perf_counter(),
                                   kind="admit", depth=state.depth,
                                   prompt_len=plen)
        sampling = state.sampling
        if sampling.mode == "greedy":
            first = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]
            dk = None
        else:
            pk, dk = jax.random.split(jnp.asarray(req.key))
            first = select_token(logits, sampling, pk[None, :])[0]
        roll = jnp.asarray(state.depth - sp, jnp.int32)
        state.cache = _admit_cache(state.cache, solo,
                                   jnp.asarray(slot, jnp.int32), roll)
        state.pad_j = state.pad_j.at[slot].set(state.depth - plen)
        state.token = state.token.at[slot].set(first)
        if state.spec_mode:
            # splice the joiner's stream into its buffer lane: prompt at
            # [depth - plen, depth), first token at depth — the verify
            # invariant every live row already satisfies. Host-built row
            # + traced-offset writes: no program minted per depth.
            rowbuf = np.zeros((state.buf.shape[1],), np.int32)
            rowbuf[state.depth - plen:state.depth] = req.prompt
            row_j = jax.lax.dynamic_update_slice(
                jnp.asarray(rowbuf), first[None],
                (jnp.asarray(state.depth, jnp.int32),))
            state.buf = state.buf.at[slot].set(row_j)
            if sampling.mode != "greedy":
                # the row's verify key chain starts at its own split(key)[1]
                # — exactly where its solo spec run's loop would start
                state.keys = state.keys.at[slot].set(dk)
        state.slots[slot] = _Slot(req=req, plen=plen, row=slot,
                                  first_ref=_SegOut(first[None]),
                                  first_idx=0, dk=dk, t0=t0)
        with self._stats_lock:
            self.joins += 1
        REGISTRY.inc("iter_joins_total")
        if req.max_new_tokens == 1:
            self._retire_finished(state)

    # -- the segment step ----------------------------------------------------

    def _set_gauges(self, state: _BatchState) -> None:
        """Live-state gauges, refreshed at every scheduling decision
        point (seed, segment boundary): what the batch looks like NOW."""
        live = sum(1 for s in state.slots if s is not None)
        width = len(state.slots)
        REGISTRY.gauge("iter_live_rows", live)
        REGISTRY.gauge("batch_occupancy", round(live / max(width, 1), 4),
                       scheduler="iter")
        REGISTRY.gauge("kv_cache_slots_in_use", state.depth * live,
                       component="iter")
        REGISTRY.gauge("queue_depth", self._queue.qsize(),
                       scheduler="iter")

    def _advance(self, state: _BatchState):
        if state.spec_mode:
            return self._advance_spec(state)
        eng = self.engine
        d = state.depth
        n = min(self.seg_steps, eng.max_seq - d)
        assert n >= 1, "active rows past max_seq (admission bug)"
        window = eng._decode_window(d + n)   # shared bucket policy
        step_keys = self._segment_keys(state, n)
        t0 = time.perf_counter()
        out, state.cache = eng._decode_seg(
            eng._run_params(), state.token, state.cache, state.pad_j,
            step_keys, sampling=state.sampling, window=window)
        state.token = out[:, -1]
        state.depth = d + n
        seg = _SegOut(out)
        t1 = time.perf_counter()
        eng._note_compiles()
        with self._stats_lock:
            self.segments_run += 1
        REGISTRY.inc("iter_segments_total")
        for s in state.slots:
            if s is not None:
                s.segs.append((seg, n))
                s.emitted += n
                if s.req.trace is not None:
                    # dispatch wall time (segments queue asynchronously
                    # on the device — the serving-thread view)
                    s.req.trace.add_span("decode", t0, t1, seg=True,
                                         steps=n, width=len(state.slots),
                                         depth=state.depth)
        self._retire_finished(state)
        self._set_gauges(state)

    def _advance_spec(self, state: _BatchState):
        """One draft-verify SEGMENT (spec batches): up to
        ``seg_steps // (draft_len + 1)`` verify forwards — the same
        device-work quantum as ``seg_steps`` single-token steps — with
        per-row acceptance, rewind, and uniform-depth re-sync all inside
        ONE compiled program (spec_decode._seg_b). Each row's emission
        is capped at its own remaining budget, so a short row never
        over-decodes and ghost rows (budget 0) stay inert.

        Costs ONE host sync per segment: the scheduler must read the
        per-row emission counts, the new per-row pads, and the new
        uniform depth to retire/admit (the price of data-dependent
        progress — same class as EOS-armed batches); the token buffer's
        device->host copy rides the same window and MUST materialize
        here, before the next segment donates the buffer."""
        eng = self.engine
        K = self.spec.draft_len
        b = len(state.slots)
        budgets = np.zeros((b,), np.int32)
        for i, s in enumerate(state.slots):
            if s is not None:
                budgets[i] = max(s.req.max_new_tokens - s.emitted, 0)
        max_verify = max(1, self.seg_steps // (K + 1))
        t0 = time.perf_counter()
        # the spec flag is routing metadata: normalize it out of the
        # static sampling arg so the segment program is shared with (and
        # byte-identical to) the solo spec engine's acceptance math
        sampling = dataclasses.replace(state.sampling, spec=False)
        buf, total, cache, pad, emitted, steps, keys = self.spec._seg_b(
            eng._run_params(), state.buf, state.cache,
            jnp.asarray(state.depth + 1, jnp.int32), state.pad_j,
            state.keys, jnp.asarray(budgets),
            max_verify=max_verify, sampling=sampling)
        state.buf, state.cache = buf, cache
        state.pad_j, state.keys = pad, keys
        seg = _SegOut(buf)
        emitted_np = np.asarray(emitted)          # THE per-segment sync
        pad_np = np.asarray(pad)
        steps_i = int(steps)
        state.depth = int(total) - 1
        _ = seg.np  # materialize: the next segment donates ``buf``
        with self._stats_lock:
            self.segments_run += 1
            self.spec_segments_run += 1
        # acceptance stats flow through the spec engine's one accounting
        # path (counters + /healthz stats + the acceptance-rate gauge),
        # so solo-spec and spec x iterbatch modes cannot diverge;
        # requests are counted at retirement (_deliver), hence 0 here
        self.spec._update_stats(0, int(emitted_np.sum()), steps_i)
        REGISTRY.inc("iter_segments_total")
        REGISTRY.inc("iter_spec_segments_total")
        self.spec._note_compiles()
        t1 = time.perf_counter()
        for s in state.slots:
            if s is not None:
                s.emitted += int(emitted_np[s.row])
                s.spec_buf = seg
                s.spec_pad = int(pad_np[s.row])
                if s.req.trace is not None:
                    s.req.trace.add_span(
                        "decode", t0, t1, seg=True, spec=True,
                        verify_steps=steps_i,
                        emitted=int(emitted_np[s.row]),
                        width=len(state.slots), depth=state.depth)
        self._retire_finished(state)
        self._set_gauges(state)

    def _segment_keys(self, state: _BatchState, n: int):
        """[n, B, 2] per-step keys. Sample rows consume THEIR OWN step
        indices (emitted-1 ... emitted-1+n of split(dk, .) — prefix-
        stable, so a late joiner's stream matches its solo run); greedy
        segments pass zeros (the program's key operand is never read)."""
        b = len(state.slots)
        if state.sampling.mode == "greedy":
            return jnp.zeros((n, b, 2), jnp.uint32)
        cols = []
        for s in state.slots:
            if s is None or s.dk is None:
                cols.append(jnp.zeros((n, 2), jnp.uint32))
            else:
                t0 = s.emitted - 1
                cols.append(jax.random.split(s.dk, t0 + n)[t0:])
        return jnp.stack(cols, axis=1)              # [n, B, 2]

    # -- retirement ----------------------------------------------------------

    def _retire_finished(self, state: _BatchState):
        eos_armed = any(s is not None and s.req.eos_id is not None
                        for s in state.slots)
        for i, s in enumerate(state.slots):
            if s is None:
                continue
            if s.req.cancelled.is_set():
                # Caller timed out and left: free the slot instead of
                # decoding dead tokens for nobody. Nothing is delivered
                # (the payload has no reader).
                state.slots[i] = None
                continue
            done = s.emitted >= s.req.max_new_tokens
            eos_at = None
            if s.req.eos_id is not None and (done or eos_armed):
                # EOS scan forces the segment fetch; only armed batches
                # pay this per-segment sync
                toks = self._row_tokens(s)
                hits = np.flatnonzero(toks == s.req.eos_id)
                if hits.size:
                    eos_at = int(hits[0])
                    done = True
            if done:
                self._deliver(state, i, s, eos_at)

    def _row_tokens(self, s: _Slot) -> np.ndarray:
        if s.spec_buf is not None:
            # spec rows: the buffer IS the stream — prompt at
            # [pad, pad + plen), everything emitted right after it
            row = s.spec_buf.np[s.row]
            start = s.spec_pad + s.plen
            n = min(s.emitted, s.req.max_new_tokens)
            return row[start:start + n]
        parts = [s.first_ref.np[s.first_idx:s.first_idx + 1]]
        parts += [seg.np[s.row] for seg, _ in s.segs]
        return np.concatenate(parts)[:s.req.max_new_tokens]

    def _deliver(self, state: _BatchState, i: int, s: _Slot, eos_at):
        """Retire the slot and hand the row to its caller. No fetch
        happens here — the caller's thread assembles the tokens (see
        ``generate``), so the scheduler keeps dispatching."""
        if eos_at is not None and eos_at + 1 < s.req.max_new_tokens:
            with self._stats_lock:
                self.eos_retires += 1
            REGISTRY.inc("iter_eos_retires_total")
        s.done_t = time.monotonic()
        s.req.payload = (s, eos_at)
        s.req.done.set()
        state.slots[i] = None
        with self._stats_lock:
            self.rows_served += 1
        if state.spec_mode:
            with self.spec._stats_lock:
                self.spec._requests += 1
        REGISTRY.inc("iter_rows_total")
