"""Request batching for serving: concurrent /generate calls share a chip.

The reference processes one request at a time end-to-end (a single
uvicorn worker looping over synchronous HTTP hops, reference
server.py:154-210). Single-stream decode leaves most of a TPU idle —
decode is weight-bandwidth-bound, so rows sharing one weight stream are
nearly free (bench cfg3: 8 rows ≈ 5x the aggregate tokens/sec, bounded
by the per-row KV-cache reads). This module multiplexes concurrent
requests onto batched decodes:

- callers block in ``generate`` while a worker thread drains a queue,
  groups compatible requests, left-pads the ragged prompts
  (``runtime.engine`` handles per-row offsets/masks), runs ONE batched
  decode, and distributes per-row results;
- **shape bucketing keeps the compile space finite** — XLA compiles one
  program per (batch, prompt_len, steps) triple, so raw request shapes
  would compile forever. Batch sizes round up to powers of two (dummy
  rows replicate the last real request and are dropped), prompt lengths
  to multiples of ``prompt_bucket`` (extra left-pad columns; the pad
  mask already excludes them), steps to multiples of ``steps_bucket``
  (extra tokens generated then truncated per row). Bucketing never
  pushes a batch past ``max_seq``: requests whose bucketed shapes can't
  coexist are split into separately-feasible sub-batches instead of
  erroring (each request individually fitting ``max_seq`` is the
  caller's contract, enforced on entry);
- requests batch when their ``SamplingConfig`` matches (greedy with
  greedy; sample rounds share one temperature/top-k/top-p policy, each
  row drawing from its OWN per-request PRNG key — the engine's per-row
  key form, ``engine._split_keys``). A policy change never starves
  anyone: the out-of-policy request is held as the guaranteed head of
  the next round, preserving FIFO.

Batching is exact in BOTH modes: greedy rows equal solo runs
token-for-token (the engine's ragged-parity guarantees), and seeded
sample rows are byte-equal to their solo runs — a row's stream depends
only on its own key (per-row categorical draws), and the PRNG splits
are prefix-stable, so neither batch composition, bucketed step
over-decode, nor dummy padding rows can perturb it (pinned by tests).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import graftsched, graftscope, tracing
from ..utils.metrics import REGISTRY, kv_block_gauges
from .engine import DecodeEngine, GenerateResult, SamplingConfig


# Static-analysis contract (tools/graftcheck): every ``jax.jit`` site in
# this module, by holding attribute — an undeclared site is a lint
# finding (a compiled-program population the recompile budget would
# silently miss).
JIT_ENTRY_POINTS = ("_merge",)

# Observability contract (tools/graftcheck scope pass + utils/graftscope):
# the prefix-round cache-merge program's dispatches are timed into the
# graftscope ring (graftscope.instrument at the jit site).
PROFILED_SCOPES = ("_merge",)


def _merge_scope_key(solos, pads, length):
    """Program key: (row count, solo cache width) — the merge compiles
    per (batch width, cache shape) pair."""
    first = solos[0][0] if isinstance(solos[0], list) else solos[0]
    return (len(solos), int(first.k.shape[-2]))

# Lock-discipline contract (tools/graftcheck locks pass): the round
# counters and the held queue head live under ``_stats_lock``.
# ``_pending`` is worker-written, but it shares a name (and a role)
# with the iteration scheduler's cross-thread head — one discipline for
# both, so the declared contract can never silently diverge.
GUARDED_STATE = {"batches_run": "_stats_lock",
                 "rows_served": "_stats_lock",
                 "_pending": "_stats_lock"}
LOCK_ORDER = ("_stats_lock",)

# Fault contract (tools/graftcheck faults pass): the admission batcher's
# blocking boundaries. The caller's ``done.wait`` carries the caller's
# own timeout; the worker's bare ``_queue.get`` is the idle park between
# rounds.
FAULT_POLICY = {
    "done.wait": ("request", "none", "TimeoutError to the caller"),
    "_queue.get": ("unbounded", "none",
                   "idle worker parks on its queue between rounds"),
}


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _bucket_batch(n: int, cap: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


@dataclasses.dataclass
class _Request:
    prompt: np.ndarray            # [S] int32
    max_new_tokens: int
    sampling: SamplingConfig
    key: Optional[jax.Array]
    done: threading.Event = dataclasses.field(default_factory=threading.Event)
    result: Optional[np.ndarray] = None   # [prompt+new] tokens
    timing: Optional[GenerateResult] = None  # the batch's engine result
    error: Optional[Exception] = None
    # request-trace propagation: the caller's ambient RequestTrace rides
    # the queue so the worker can attribute queue wait and the shared
    # round phases (via tracing.fanout) to every row it serves
    trace: Optional[object] = None
    t_submit: float = 0.0


class BatchingEngine:
    """Thread-safe batched front end over a ``DecodeEngine``.

    ``generate`` may be called concurrently from many threads (the
    serving stack runs one thread per request); calls block until their
    tokens are ready. One worker thread owns all device dispatch, so JAX
    sees single-threaded use.
    """

    def __init__(self, engine: DecodeEngine, max_batch: int = 8,
                 max_wait_ms: float = 5.0, prompt_bucket: int = 16,
                 steps_bucket: int = 32, prefix=None, spec=None):
        """``prefix`` (optional ``PrefixCachingEngine`` wrapping the SAME
        underlying engine) composes cross-request KV reuse with batching:
        each row prefills solo through the prefix store (hit or miss at
        its own depth), the per-row caches merge into one left-padded
        batched cache (a roll by each row's pad — cache slots shift with
        positions, so the merged state is exactly what a batched prefill
        would have produced), and ONE batched decode serves all rows.
        Single-request rounds route through ``prefix.generate`` directly,
        preserving the solo path's speculation composition.

        ``spec`` (optional ``SpecDecodeEngine`` wrapping the SAME engine)
        composes speculation with batching: requests whose policy carries
        ``SamplingConfig.spec`` gather into their own rounds (the flag is
        part of policy equality, so the existing FIFO-preserving
        policy-change handling applies unchanged) and decode through the
        spec engine's BATCHED verify loop — per-row acceptance with
        uniform-depth re-sync, every row byte-equal to its solo
        speculative run (greedy and seeded sample; see
        runtime.spec_decode). Spec rounds reserve ``draft_len`` cache
        slots of verify-write headroom when bucketing shapes, and bypass
        the prefix store (its first-token merge is solo-round-only)."""
        if prefix is not None and prefix.plain is not engine:
            raise ValueError("prefix must wrap the same engine instance")
        if spec is not None and spec.plain is not engine:
            raise ValueError("spec must wrap the same DecodeEngine (shared "
                             "weights/programs), got a different instance")
        self.engine = engine
        self.prefix = prefix
        self.spec = spec
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.prompt_bucket = prompt_bucket
        self.steps_bucket = steps_bucket
        self._merge = graftscope.instrument(
            jax.jit(self._merge_impl), "batcher._merge",
            key_fn=_merge_scope_key)
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._pending: Optional[_Request] = None  # held head of next round
        self._stats_lock = graftsched.lock(
            "batcher.BatchingEngine._stats_lock")
        self.batches_run = 0
        self.rows_served = 0
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    # -- caller side ---------------------------------------------------------

    def generate(self, prompt_ids, max_new_tokens: int,
                 sampling: SamplingConfig = SamplingConfig(),
                 key: Optional[jax.Array] = None,
                 timeout: Optional[float] = None) -> GenerateResult:
        """Single-sequence generate; blocks until the batch containing it
        completes. Accepts [S] or [1, S] prompts (a batcher batches
        *requests*; pre-batched multi-row input should go straight to the
        engine)."""
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must be non-empty")
        if len(prompt) + max_new_tokens > self.engine.max_seq:
            # per-request contract, checked on the caller's thread so the
            # error is immediate and names THIS request's numbers (the
            # worker plans sub-batches assuming every request fits)
            raise ValueError(
                f"prompt_len={len(prompt)} + max_new_tokens="
                f"{max_new_tokens} exceeds max_seq={self.engine.max_seq}")
        if sampling.mode != "greedy" and key is None:
            # also caller-thread: a keyless sample request cannot join the
            # per-row-key batch contract (and the engine would reject it
            # later anyway, from the worker thread)
            raise ValueError(
                "sample-mode requests must carry a per-request PRNG key")
        if sampling.spec:
            # caller-thread eligibility: a flagged request speculation
            # cannot serve exactly must be refused HERE with its own
            # numbers, not mid-round (rule defined once, on the engine)
            if self.spec is None:
                raise ValueError(
                    "sampling.spec requested but this batcher has no "
                    "speculative engine attached (pass spec= at "
                    "construction)")
            self.spec.check_request(len(prompt), max_new_tokens)
        req = _Request(prompt=prompt, max_new_tokens=max_new_tokens,
                       sampling=sampling, key=key,
                       trace=tracing.current_trace(),
                       t_submit=time.perf_counter())
        self._queue.put(req)
        REGISTRY.gauge("queue_depth", self._queue.qsize(),
                       scheduler="admission")
        if not req.done.wait(timeout):
            raise TimeoutError("batched generate timed out")
        if req.error is not None:
            raise req.error
        inner = req.timing
        return GenerateResult(
            tokens=req.result[None, :], prompt_len=len(prompt),
            prefill_seconds=inner.prefill_seconds,
            decode_seconds=inner.decode_seconds,
            new_tokens=max_new_tokens,
            decode_steps=inner.decode_steps)

    # -- worker side ---------------------------------------------------------

    def _gather(self) -> List[_Request]:
        """Block for the first request, then collect batchable peers for
        up to ``max_wait_ms``. Requests group when their SamplingConfig
        matches exactly (sample rows each draw from their own key, so a
        shared policy is the only batching requirement). An out-of-policy
        request ends the round and is HELD as the next round's first
        request — re-queueing it at the tail would let sustained traffic
        of another policy starve it forever."""
        with self._stats_lock:
            first, self._pending = self._pending, None
        if first is None:
            first = self._queue.get()
        batch = [first]
        if (first.sampling.mode != "greedy" and self.prefix is not None
                and getattr(self.prefix, "_spec", None) is not None):
            # with speculation attached to the prefix engine, a solo
            # sample round streams rejection-sampled tokens while a
            # batched round would use the plain per-row path — the same
            # seed would emit different tokens depending on concurrent
            # traffic. Keep such requests solo so streams stay a pure
            # function of (prompt, params, seed, config). (Serving
            # cannot reach this: SPEC_DECODE x MAX_BATCH is refused at
            # startup — this guards the library composition.)
            return batch
        deadline = _monotonic() + self.max_wait_s
        while len(batch) < self.max_batch:
            remaining = deadline - _monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt.sampling == first.sampling:
                batch.append(nxt)
            else:
                with self._stats_lock:
                    self._pending = nxt
                break
        return batch

    def _plan(self, batch: List[_Request]) -> List[List[_Request]]:
        """Split a gathered batch into bucket-feasible sub-batches.

        Bucketing rounds the longest prompt up, so two requests that each
        fit ``max_seq`` may not fit TOGETHER (a 500-token prompt next to
        a 90-token-generation request at max_seq=512). Greedy first-fit
        keeps arrival order within each sub-batch.
        """
        subs: List[List[_Request]] = []
        for req in batch:
            placed = False
            for sub in subs:
                trial = sub + [req]
                if self._shapes(trial) is not None:
                    sub.append(req)
                    placed = True
                    break
            if not placed:
                subs.append([req])
        return subs

    def _shapes(self, batch: List[_Request]):
        """(s_max, steps) for a candidate batch, or None if infeasible.

        Prompt bucketing is capped so bucket padding alone never pushes
        past max_seq; a batch is feasible iff the capped bucket still
        covers its longest prompt. Spec rounds additionally reserve
        ``draft_len`` slots of verify-write headroom (the spec engine's
        own generate guard, applied to the round's shared shape).
        """
        raw_s = max(len(r.prompt) for r in batch)
        need = max(r.max_new_tokens for r in batch)
        reserve = self.spec.draft_len if batch[0].sampling.spec else 0
        s_max = min(_round_up(raw_s, self.prompt_bucket),
                    self.engine.max_seq - need - reserve)
        if s_max < raw_s:
            return None
        steps = min(_round_up(need, self.steps_bucket),
                    self.engine.max_seq - s_max - reserve)
        return s_max, steps

    def _loop(self):
        while True:
            gathered = self._gather()
            for batch in self._plan(gathered):
                try:
                    self._run(batch)
                except Exception as e:  # noqa: BLE001 — delivered per-request
                    for req in batch:
                        req.error = e
                        req.done.set()

    @staticmethod
    def _merge_impl(solos, pads, length):
        """Per-row solo caches -> one batched left-padded cache.

        Row i's solo cache holds positions ``[0, plen_i)`` at slots
        ``[0, plen_i)``; the batched layout wants them at slots
        ``[pad_i, s_max)``. A roll by ``pad_i`` along the slot axis does
        exactly that (the wrapped garbage lands in the pad prefix, which
        ``k_valid_from`` masks, and beyond ``s_max``, which ``kv_length``
        masks until decode overwrites it). Handles plain, staged (list),
        and fused (empty ``v``) cache forms.
        """
        from ..ops.attention import KVCache

        def one(row_caches):
            def cat(leaves):
                if leaves[0].ndim <= 1:          # fused placeholder v
                    return leaves[0]
                return jnp.concatenate(
                    [jnp.roll(x, pads[i], axis=-2)
                     for i, x in enumerate(leaves)], axis=1)
            return KVCache(k=cat([c.k for c in row_caches]),
                           v=cat([c.v for c in row_caches]),
                           length=length)

        if isinstance(solos[0], list):           # staged engine
            return [one([s[j] for s in solos]) for j in range(len(solos[0]))]
        return one(solos)

    def _run_prefix(self, batch: List[_Request], ids: np.ndarray,
                    pad: np.ndarray, steps: int):
        """Batched decode over per-row prefix-store prefills (greedy
        rounds only — the first-token merge below is argmax; sample
        batches bypass the prefix store, see _run)."""
        t0 = _monotonic()
        states = []
        for req in batch:
            # per-row store prefill: attribute THIS row's span to its own
            # trace, not the whole round's (the batched decode below
            # still fans out to everyone)
            with tracing.use_trace(req.trace):
                logits, cache, _ = self.prefix.prefill_state(req.prompt)
            states.append((logits, cache))
        while len(states) < ids.shape[0]:        # dummy rows replicate last
            # (their pad/ids were already replicated from the same source
            # row in _run, so a dummy row's cache, pad and positions are
            # self-consistent — it is a full clone of the last real row)
            states.append(states[len(batch) - 1])
        first = jnp.argmax(jnp.concatenate([s[0] for s in states], axis=0),
                           axis=-1).astype(jnp.int32)
        pads_j = jnp.asarray(pad)
        cache = self._merge([s[1] for s in states], pads_j,
                            jnp.asarray(ids.shape[1], jnp.int32))
        eng = self.engine
        return eng._decode_and_pack(
            eng._run_params(), ids, pad, pads_j if pad.any() else None,
            first, cache, jax.random.PRNGKey(0), steps,
            batch[0].sampling, ids.shape[1], _monotonic() - t0)

    def _run(self, batch: List[_Request]):
        """Trace plumbing around ``_run_inner``: queue wait is stamped
        per request, then the round's shared device phases (the engine's
        prefill/decode spans) fan out into every row's trace."""
        t_now = time.perf_counter()
        traces = [r.trace for r in batch if r.trace is not None]
        for r in batch:
            if r.trace is not None:
                r.trace.add_span("queue_wait", r.t_submit, t_now,
                                 scheduler="admission")
        ctx = (tracing.use_trace(tracing.fanout(traces)) if traces
               else tracing.use_trace(None))
        with ctx:
            self._run_inner(batch)

    def _run_inner(self, batch: List[_Request]):
        if batch[0].sampling.spec:
            # spec-flagged rounds (any size, solo included — the stream
            # must be a pure function of the request, never of whether a
            # prefix store happened to be attached) decode through the
            # spec engine's batched verify loop: per-row acceptance +
            # uniform-depth re-sync, each row byte-equal to its solo
            # speculative run (greedy and seeded sample).
            self._run_spec(batch)
            return
        if self.prefix is not None and len(batch) == 1:
            # solo rounds keep the full single-stream prefix path
            # (including its speculation composition) and true shapes
            req = batch[0]
            result = self.prefix.generate(req.prompt, req.max_new_tokens,
                                          sampling=req.sampling, key=req.key)
            self._deliver(batch, result)
            return

        s_max, steps = self._shapes(batch)  # planned feasible: not None
        b = _bucket_batch(len(batch), self.max_batch)
        ids, pad = self._bucket_rows(batch, b, s_max)
        # the round's KV arena in the shared block denomination
        # (utils.metrics.kv_block_gauges): live while the round runs,
        # back to 0 at delivery — an idle batcher holds no KV
        kv_block_gauges("batcher", b * (s_max + steps),
                        b * self.engine._cache_seq)

        greedy = batch[0].sampling.mode == "greedy"
        if self.prefix is not None and greedy:
            result = self._run_prefix(batch, ids, pad, steps)
        else:
            if greedy:
                key = batch[0].key  # never consumed by greedy draws
            else:
                # Sample rounds bypass the prefix store: its first-token
                # merge is argmax-only.
                key = self._row_keys(batch, b)
            result = self.engine.generate(ids, steps,
                                          sampling=batch[0].sampling, key=key,
                                          pad=pad)
        self._deliver(batch, result, padded_rows=b - len(batch))

    @staticmethod
    def _bucket_rows(batch: List[_Request], b: int, s_max: int):
        """Right-aligned [b, s_max] prompt matrix + per-row left-pad for
        one bucketed round; dummy rows replicate the last real request.
        THE round-shape builder — plain and spec rounds share it, so a
        change to dummy-row policy cannot diverge between them."""
        ids = np.zeros((b, s_max), dtype=np.int32)
        pad = np.zeros((b,), dtype=np.int32)
        for i in range(b):
            r = batch[min(i, len(batch) - 1)]
            ids[i, s_max - len(r.prompt):] = r.prompt
            pad[i] = s_max - len(r.prompt)
        return ids, pad

    @staticmethod
    def _row_keys(batch: List[_Request], b: int):
        """Per-row key stack: row i's stream derives only from its own
        request key (dummy rows replicate the last real key — their
        draws are dropped), so batched rows are byte-equal to solo runs
        (engine._split_keys contract)."""
        keys = [r.key for r in batch]
        keys += [keys[-1]] * (b - len(batch))
        return jnp.stack([jnp.asarray(k) for k in keys])

    def _run_spec(self, batch: List[_Request]):
        """One bucketed round through ``SpecDecodeEngine.generate``'s
        batched path. Shapes bucket exactly like plain rounds (power-of-
        two width, prompt/steps buckets — with draft_len headroom, see
        ``_shapes``); rows past a request's own ``max_new_tokens`` are
        bucket over-decode and truncated in ``_deliver``, leaving the
        kept prefix byte-equal to the solo spec run (per-verify RNG
        consumption is budget-independent, and verify writes never touch
        slots before the row's existing content)."""
        s_max, steps = self._shapes(batch)  # planned feasible: not None
        b = _bucket_batch(len(batch), self.max_batch)
        ids, pad = self._bucket_rows(batch, b, s_max)
        if batch[0].sampling.mode == "greedy":
            key = None
        else:
            key = self._row_keys(batch, b)
        result = self.spec.generate(
            ids, steps, sampling=batch[0].sampling, key=key, pad=pad,
            # acceptance stats count what callers are SERVED: dummy
            # rows and bucket over-decode are shape tax, not traffic
            delivered=(len(batch),
                       sum(r.max_new_tokens for r in batch)))
        self._deliver(batch, result, padded_rows=b - len(batch))

    def _deliver(self, batch: List[_Request], result: GenerateResult,
                 padded_rows: int = 0):
        with self._stats_lock:
            self.batches_run += 1
            self.rows_served += len(batch)
        REGISTRY.inc("decode_batches_total")
        REGISTRY.inc("batched_requests_total", value=len(batch))
        REGISTRY.inc("batched_rows_padded_total", value=padded_rows)
        occupancy = round(len(batch) / (len(batch) + padded_rows), 4)
        depth = self._queue.qsize()
        REGISTRY.gauge("batch_occupancy", occupancy, scheduler="admission")
        # graftscope occupancy time series (the /debug/profile trajectory
        # behind the instantaneous gauges) — one qsize read shared with
        # the gauge below, so the two views cannot disagree
        graftscope.sample("batch_occupancy", occupancy,
                          scheduler="admission")
        graftscope.sample("queue_depth", depth, scheduler="admission")
        # round done: its arena is released (an idle batcher must not
        # keep reporting the last round's blocks — same invariant as
        # the engine component's end-of-generate reset)
        width = len(batch) + padded_rows
        kv_block_gauges("batcher", 0, width * self.engine._cache_seq)
        REGISTRY.gauge("queue_depth", depth, scheduler="admission")
        for i, req in enumerate(batch):
            # row_tokens strips the engine-reported pad — OUR bucket pad
            # plus any chunk-alignment pad the engine added on top
            # (DecodeEngine prefill_chunk); slicing by the local ``pad``
            # would leak chunk-pad zeros into responses
            row = result.row_tokens(i)
            req.result = row[:len(req.prompt) + req.max_new_tokens]
            req.timing = result
            req.done.set()


def _monotonic() -> float:
    import time
    return time.monotonic()
