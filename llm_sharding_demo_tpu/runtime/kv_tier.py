"""grafttier: the host-RAM KV spill tier below the device pool.

Helix-style interactive serving is KV-capacity-bound: the content-keyed
prefix registry (runtime/kv_pool.py) is worth far more than one
device's HBM, yet before this module a cold zero-ref prefix entry was
simply LRU-evicted and re-prefilled from scratch on its next hit. The
tier turns that cliff into a ladder:

- **demote** (``HostKVTier.demote_lru``): when allocation pressure
  would LRU-evict a prefix entry (``BlockAllocator._demote_pressure``)
  or the store's capacity trim fires (``PrefixCachingEngine``), the
  entry's blocks are copied to bounded host-RAM numpy buffers as RAW
  plane bytes — quantized pools spill codes + per-block scales, never
  dequantized f32, so an int8 spill moves ~4x fewer bytes — and the
  registry entry moves down a tier under its ORIGINAL content key.
- **promote** (``HostKVTier.promote``): an affinity hit on a demoted
  key (the prefix store's ``_lookup`` walk) allocates fresh device
  blocks, ``device_put``s the host bytes back, and re-registers the
  entry under the same key — so ``prefill_shared``'s zero-copy
  reference semantics hold unchanged after a round trip, and a
  promoted block's decode output is byte-identical to a never-demoted
  run (pinned by tests/test_kv_tier.py for every storage regime).
- **LRU-to-oblivion**: the host budget (``KV_HOST_BLOCKS``, the
  serving knob) is a hard bound; admitting a new demotion discards the
  host tier's own LRU entries, and an entry too large for the whole
  budget falls back to plain device eviction (typed, never an error).

Tier conservation (the blocks_in_use+blocks_free==blocks_total
discipline, per tier): ``host_blocks_in_use == sum(entry blocks)``,
``entries == demotions - promotions - discards``, occupancy never
exceeds the budget — checked at every tier boundary when the owning
allocator sanitizes (GRAFTSAN=1), raising ``GraftsanError`` with the
numbers. Byte conservation rides graftmem: each host entry is a
tracked ``host_spill`` holding (bytes MEASURED from the numpy buffers,
never shape arithmetic), so a demote's ``mem_alloc`` and the matching
promote/discard ``mem_free`` conserve ledger bytes pairwise and
``/debug/memory``'s ``host_spill`` component equals the
``/healthz kv_pool_stats`` tier block (pinned).

Lock discipline: the tier's ``_lock`` is a LEAF — never held across
allocator (``_lock``) or device (``_dev_lock``) work. Demote sequences
lease (allocator lock) -> spill (device lock) -> pop (allocator lock)
-> install (tier lock); promote pops the host entry first, then does
device/allocator work with the tier lock released. A promote-triggered
allocation may recursively demote OTHER entries without deadlock
precisely because of this ordering.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import graftmem, graftsched, grafttime
from ..utils.metrics import REGISTRY
from .kv_pool import GraftsanError, PoolExhausted

# Tier contract (tools/graftcheck tier pass): the declared tier
# topology, one entry per tier below "device". ``budget`` names the
# serving knob that bounds it, ``holding`` the graftmem-tracked store
# attribute (must appear in MEMORY_LEDGER — the tier-ledger-gap rule),
# ``eviction`` the final-tier policy, and the two events are the
# timeline kinds its demote/promote scopes must emit (the
# tier-event-drift rule).
TIER_POLICY = {
    "host": {
        "below": "device",
        "budget": "KV_HOST_BLOCKS",
        "eviction": "lru-to-oblivion",
        "holding": "_entries",
        "component": "host_spill",
        "demote_event": "tier_demote",
        "promote_event": "tier_promote",
    },
}

# The only scopes allowed to move block bytes BETWEEN tiers (call
# ``spill_blocks``/``fill_blocks`` or pop/install host entries) — the
# tier pass flags tier movement outside them, and a declared scope
# that stopped moving anything is a stale finding.
SPILL_SCOPES = ("HostKVTier.demote_lru", "HostKVTier.promote")

# Registry-handoff contract (tools/graftcheck fleet pass): promotion
# re-registers the demoted entry under its ORIGINAL content key (and
# answers a lost promote race from the device registry), so the tier
# is a consumer of the adoption surface — the only one here.
HANDOFF_SCOPES = ("HostKVTier.promote",)

# The tier never moves blocks through the gather/scatter movers: its
# device traffic is the pool's raw-plane ``spill_blocks`` /
# ``fill_blocks``, which carry their own graftsan table checks under
# ``_dev_lock`` (declared empty on purpose — the fleet pass requires
# the adoption boundary to state its mover contract explicitly).
POOL_MOVER_SCOPES = ()

# Timeline contract (tools/graftcheck timeline pass): tier movements
# land on the unified causal stream — a demotion storm is only
# diagnosable beside the admissions/evictions that provoked it, and a
# promote's dur_ms IS the affinity hit's stall.
TIMELINE_EVENTS = {
    "tier_demote": "HostKVTier.demote_lru",
    "tier_promote": "HostKVTier.promote",
}

# Memory-ledger contract (tools/graftcheck memory pass +
# utils/graftmem): every demoted entry's host buffers are tracked
# ``host_spill`` holdings under the ``_entries`` store — bytes
# measured from the actual numpy buffers at demote time, released at
# promote/discard, so the ledger conserves across every tier move.
MEMORY_LEDGER = {"_entries": "host_spill"}

# Lock-discipline contract (tools/graftcheck locks pass): the entry
# store, occupancy, and movement counters are written by demoting
# allocator threads and promoting lookup threads concurrently — all
# under the tier's own ``_lock`` (a leaf: see the module docstring).
GUARDED_STATE = {
    "_entries": "_lock", "_blocks_in_use": "_lock",
    "demotions": "_lock", "promotions": "_lock", "discards": "_lock",
    "_promote_ms": "_lock",
}
LOCK_ORDER = ("_lock",)


@dataclasses.dataclass
class _HostEntry:
    """One demoted prefix entry: the raw plane bytes of its blocks
    (codes, plus scales for quantized pools), the device block count
    they stand for, and the graftmem handle measuring them."""
    codes: np.ndarray
    scales: Optional[np.ndarray]
    n_blocks: int
    mem_handle: int


class HostKVTier:
    """Bounded host-RAM store of demoted prefix entries, LRU-ordered
    (insertion order IS the LRU order; promotes pop). Attach below a
    ``KVBlockPool`` with ``pool.attach_tier(tier)``."""

    def __init__(self, host_blocks: int):
        if host_blocks < 1:
            raise ValueError(
                f"host_blocks={host_blocks} must be >= 1 (a zero-block "
                "tier is 'no tier' — leave it unattached instead)")
        self.host_blocks = host_blocks
        self._lock = graftsched.rlock("kv_tier.HostKVTier._lock")
        # content-key -> _HostEntry; OrderedDict insertion order is the
        # LRU order of the HOST tier (oldest demotion discards first)
        self._entries: "OrderedDict[bytes, _HostEntry]" = OrderedDict()
        self._blocks_in_use = 0
        self.demotions = 0
        self.promotions = 0
        self.discards = 0
        self._promote_ms = 0.0

    # -- conservation (per-tier graftsan) ------------------------------------

    def _check_locked(self, boundary: str) -> None:
        """Per-tier conservation at a boundary (GRAFTSAN discipline):
        occupancy equals the sum of live entries' blocks, the entry
        count equals the movement ledger, and occupancy respects the
        budget. A violation is an accounting bug — raise with the
        numbers, not a silent drift."""
        held = sum(e.n_blocks for e in self._entries.values())
        if held != self._blocks_in_use:
            raise GraftsanError(
                f"[tier:{boundary}] host-block conservation broken: "
                f"{held} blocks held by entries != {self._blocks_in_use} "
                "in use")
        moved = self.demotions - self.promotions - self.discards
        if len(self._entries) != moved:
            raise GraftsanError(
                f"[tier:{boundary}] entry conservation broken: "
                f"{len(self._entries)} entries != {self.demotions} "
                f"demotions - {self.promotions} promotions - "
                f"{self.discards} discards")
        if self._blocks_in_use > self.host_blocks:
            raise GraftsanError(
                f"[tier:{boundary}] budget broken: {self._blocks_in_use}"
                f" blocks in use > {self.host_blocks} budget")

    # -- demotion ------------------------------------------------------------

    def demote_lru(self, pool) -> bool:
        """Move the device pool's LRU prefix entry down to this tier.
        Returns True when an entry moved (its device blocks freed);
        False when there is nothing to demote, the entry exceeds the
        whole host budget (caller falls back to plain eviction — typed,
        never an error), or the entry changed under the lease (the
        stale host copy is discarded). Sequencing per the module
        docstring: allocator lease -> device spill -> allocator pop ->
        tier install, no lock held across stages."""
        alloc = pool.allocator
        lease = alloc.lease_lru_prefix()
        if lease is None:
            return False
        key, ids = lease
        n = len(ids)
        if n > self.host_blocks:
            alloc.free(ids)
            return False
        codes, scales = pool.spill_blocks(ids)
        if not alloc.demote_pop_prefix(key, ids):
            # raced: the entry was dropped/evicted/re-registered since
            # the lease — our host copy is stale, discard it
            alloc.free(ids)
            return False
        alloc.free(ids)
        handle = graftmem.track(self, "_entries", "host_spill",
                                (codes, scales))
        dropped: List[_HostEntry] = []
        sanitize = alloc.sanitize
        with self._lock:
            prior = self._entries.pop(key, None)
            if prior is not None:
                # same content demoted twice (re-prefilled between the
                # moves): the newer bytes replace the stale copy, which
                # leaves as a discard so the movement ledger balances
                self._blocks_in_use -= prior.n_blocks
                self.discards += 1
                dropped.append(prior)
            # LRU-to-oblivion: the budget is hard — admitting this
            # entry discards the host tier's own coldest entries
            while (self._blocks_in_use + n > self.host_blocks
                   and self._entries):
                _, old = self._entries.popitem(last=False)
                self._blocks_in_use -= old.n_blocks
                self.discards += 1
                dropped.append(old)
            self._entries[key] = _HostEntry(codes, scales, n, handle)
            self._blocks_in_use += n
            self.demotions += 1
            in_use = self._blocks_in_use
            n_entries = len(self._entries)
            if sanitize:
                self._check_locked("demote")
        # ledger + bus emission outside the hold (the graftmem
        # discipline: the apparatus stays off its own critical section)
        for old in dropped:
            graftmem.release(old.mem_handle)
        REGISTRY.inc("tier_demotions_total")
        grafttime.emit("tier_demote", blocks=n, host_blocks=in_use,
                       host_entries=n_entries)
        return True

    # -- promotion -----------------------------------------------------------

    def has(self, key: bytes) -> bool:
        """Is ``key`` demoted here? (No LRU effect — peeking is free.)"""
        with self._lock:
            return key in self._entries

    def promote(self, pool, key: bytes) -> Optional[Tuple[int, ...]]:
        """Promote a demoted entry back into the device pool ahead of
        admission: allocate fresh blocks (which may recursively demote
        OTHER cold entries — the tier lock is not held), ``device_put``
        the host bytes back, and re-register under the SAME content
        key. Returns the block ids with one caller ref per block (the
        ``lookup_prefix`` contract — release with ``free``), or None
        when the key is not demoted here or the device pool cannot
        host it right now (the entry stays demoted; the caller walks
        on to shallower depths)."""
        alloc = pool.allocator
        if alloc.has_prefix(key):
            # already resident (a concurrent promote or re-prefill won
            # the race): the host copy is redundant — drop it and
            # answer from the device registry
            with self._lock:
                entry = self._entries.pop(key, None)
                if entry is not None:
                    self._blocks_in_use -= entry.n_blocks
                    self.discards += 1
                    if alloc.sanitize:
                        self._check_locked("promote_redundant")
            if entry is not None:
                graftmem.release(entry.mem_handle)
            return alloc.lookup_prefix(key)
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._blocks_in_use -= entry.n_blocks
        if entry is None:
            return None
        t0 = time.perf_counter()
        try:
            ids = alloc.alloc(entry.n_blocks)
        except PoolExhausted:
            # the device pool cannot host the entry even after demoting
            # everything demotable: put the host copy back (front of
            # the LRU — it just missed, it is warm) and report a miss
            with self._lock:
                self._entries[key] = entry
                self._entries.move_to_end(key, last=False)
                self._blocks_in_use += entry.n_blocks
                if alloc.sanitize:
                    self._check_locked("promote_refused")
            return None
        pool.fill_blocks(ids, entry.codes, entry.scales)
        alloc.register_prefix(key, ids)
        dur_ms = (time.perf_counter() - t0) * 1e3
        sanitize = alloc.sanitize
        with self._lock:
            self.promotions += 1
            self._promote_ms += dur_ms
            in_use = self._blocks_in_use
            if sanitize:
                self._check_locked("promote")
        graftmem.release(entry.mem_handle)
        REGISTRY.inc("tier_promotions_total")
        grafttime.emit("tier_promote", blocks=entry.n_blocks,
                       host_blocks=in_use, dur_ms=round(dur_ms, 3))
        return tuple(ids)

    # -- observability -------------------------------------------------------

    def note_gauges(self, component: str = "pool") -> None:
        with self._lock:
            in_use = self._blocks_in_use
        REGISTRY.gauge("kv_host_blocks_in_use", in_use,
                       component=component)
        REGISTRY.gauge("kv_host_blocks_total", self.host_blocks,
                       component=component)

    def graftsan_check(self, boundary: str = "explicit") -> None:
        """Run the per-tier conservation check on demand (tests and
        the /healthz handler's tier drift assert)."""
        with self._lock:
            self._check_locked(boundary)

    def stats(self) -> Dict[str, object]:
        """The tier block ``KVBlockPool.stats`` merges (and therefore
        what ``/healthz kv_pool_stats`` serves): occupancy in the
        device pool's block denomination, the movement ledger, and the
        MEASURED host bytes (``graftmem.holding_bytes`` over the
        ``host_spill`` entries — the same single bookkeeping path
        ``/debug/memory`` reads, so the two surfaces cannot drift)."""
        with self._lock:
            out = {
                "host_blocks_total": self.host_blocks,
                "host_blocks_in_use": self._blocks_in_use,
                "host_entries": len(self._entries),
                "demotions": self.demotions,
                "promotions": self.promotions,
                "discards": self.discards,
                "promote_ms_total": round(self._promote_ms, 3),
            }
        out["host_bytes"] = graftmem.holding_bytes(self, "_entries")
        return out
